"""SolveEngine: the continuously-batched, AOT-cached, shape-bucketed solve
service — the facade over serve's three independently-scalable pieces
(docs/SERVING.md has the full lifecycle):

* **scheduler.py** — admission into in-flight bucket batches, async
  host→device staging (`jax.device_put` at submit, ahead of dispatch),
  overlapping dispatch of consecutive buckets with a bounded in-flight
  window, deadline flushes.  ``ServeConfig.scheduler="sync"`` is the PR 4
  stop-and-go loop, kept as the measured A/B baseline (serve/loadgen.py).

* **cache.py** — the AOT executable cache: every program the engine runs
  is compiled once via ``jax.jit(fn).lower(ShapeDtypeStruct...).compile()``
  under an explicit key (op, dtype, shape-bucket, mesh/topology,
  config-hash), with hit/miss counters that make "steady-state traffic
  hits zero recompiles" assertable.  ``ServeConfig.persist_dir`` adds the
  disk tier: compiled executables are serialized there so replicas and
  restarts skip warmup entirely (``compiles == 0`` on a warm dir — the
  cold-start gate of `make serve-smoke`); corrupt or stale entries fall
  back to compile-and-overwrite, never to the caller.

* **executor.py** — dispatch, donation, fault containment, result
  landing.  Batched dispatch does NOT synchronize; landing stamps each
  request's queue-wait/device latency split into the stats.

The engine itself keeps the public surface (`submit`/`pump`/`drain`/
`solve`/`warmup`/`cache_stats`/`emit_stats`) plus the policies that need
the whole picture: request validation, the host-side ``serve::ingest``
fault tap (a planted fault corrupts exactly one request and never bakes
into a cached executable), bucket resolution, and the config hash.

Donation (PR 4 contract, unchanged): engine-built batch buffers only,
TPU-only by default; posv donates its RHS batch, inv its operand batch,
lstsq nothing — its (m, nrhs) RHS cannot alias the (n, nrhs) solution and
XLA would silently drop the declaration.  ``SolveEngine(validate=True)``
asserts the compiled input_output_alias honors every declared donation at
cache-insert time (fresh compiles only — a disk-loaded executable was
validated by the process that compiled it).
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import Optional

import jax
import jax.numpy as jnp

from capital_tpu.models import blocktri
from capital_tpu.obs import spans
from capital_tpu.ops import batched_small, blocktri_small, lapack, update_small
from capital_tpu.parallel.topology import Grid
from capital_tpu.robust import faultinject
from capital_tpu.robust.config import RobustConfig, RobustInfo
from capital_tpu.serve import api, batching, stats
from capital_tpu.serve.cache import ExecutableCache
from capital_tpu.serve.factorcache import FactorCache
from capital_tpu.serve.executor import (  # noqa: F401  (re-exported API)
    Executor,
    Response,
    Ticket,
    _Pending,
)
from capital_tpu.serve.scheduler import Scheduler
from capital_tpu.utils import tracing

SCHEDULERS = ("continuous", "sync")


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine policy knobs.

    buckets: the n ladder (SPD dimension / lstsq columns).
    rows_buckets: the lstsq m ladder (requests bucket at m + column-pad).
    nrhs_buckets: the RHS-columns ladder.
    nblocks_buckets: the posv_blocktri chain-length ladder (number of
        diagonal blocks; padded chains append identity blocks with zero
        couplings — bitwise-inert, the chain is sequential).
    block_buckets: the posv_blocktri block-size ladder (per-block b;
        padded blocks embed diag(D_i, I)).  Both join the config hash
        with the dense ladders — the blocktri buckets AOT-cache alongside
        dense buckets under the same discipline.
    border_buckets: the posv_arrowhead border-width ladder (s — the
        number of dense corner rows coupling the chain to the corner).
        A structural rank, not an RHS count, so it gets its own ladder
        rather than riding nrhs_buckets; padded borders append zero rows
        and the corner embeds diag(S, I) (batching._pad_arrowhead).
        Joins the config hash with the other ladders.
    blocktri_impl: which chain ALGORITHM the posv_blocktri bucket
        programs compile (models/blocktri.ALGORITHMS): 'auto' lets
        posv's dispatch pick (the partitioned Spike driver above
        PARTITION_MIN_NBLOCKS when the kernel flavor is auto too),
        'partitioned' forces the split, 'scan' pins the sequential scan.
        Joins the config hash — a partitioned and a sequential engine
        compile different programs and must never share cache entries.
    blocktri_partitions: requested partition count for the partitioned
        chain driver (0 = resolve_partitions default, the largest
        divisor of nblocks ≤ √nblocks; requests decrement to a valid
        divisor per bucket).  Joins the config hash for the same reason
        — the partition count is baked into every compiled chain
        program's geometry.
    max_batch: per-bucket batch capacity — one executable per bucket at
        this fixed batch size; also the submit-time flush threshold.
    max_delay_s: oldest-request age that forces a flush at pump() — the
        latency bound a half-full batch is allowed to cost.
    precision: matmul precision inside the kernels ('highest' matches the
        models/ defaults; see CholinvConfig.precision).
    robust: attach per-request breakdown flagging (batched: detect-only;
        oversize lstsq: the full shifted-CholeskyQR recovery).
    donate: donate engine-built batch inputs to their executables; None =
        auto (TPU yes, CPU no — the CPU runtime warns and ignores).
    oversize: 'models' routes beyond-ladder requests through the unbatched
        models/ paths; 'reject' fails them (a hard-real-time posture where
        an unexpected compile is worse than an error).
    small_n_impl: which batched implementation the bucket executables use
        (serve/api.batched): 'auto' resolves per bucket at trace time
        (small VMEM-eligible posv/lstsq buckets take the fused batched-
        grid pallas kernels of ops/batched_small, the rest vmap-over-
        LAPACK); 'vmap' / 'pallas' / 'pallas_split' force one route for
        every bucket.  Joins the config hash — two engines differing here
        compile different programs and must never share cache entries.
    tail_fuse_depth: CholinvConfig.tail_fuse_depth for the oversize single
        route (fused recursion tail, ops/pallas_tpu.fused_tail; 0 =
        unfused).  Joins the config hash: a fused and an unfused engine
        compile different programs and must never share cache entries —
        the zero-recompile smoke stays green precisely because the knob
        is keyed, not hidden.
    scheduler: 'continuous' (default) overlaps staging/dispatch/landing
        across consecutive buckets (serve/scheduler.py); 'sync' is the
        PR 4 stop-and-go flush, kept as the loadgen A/B baseline.  NOT in
        the config hash: both modes run byte-identical programs, so they
        share cache entries (and a persistent dir) on purpose.
    max_inflight: continuous mode's bound on unlanded dispatched batches;
        the oldest is collected before exceeding it.
    persist_dir: disk directory for the persistent AOT cache tier
        (serve/cache.py); None keeps the cache in-memory only.  NOT in
        the config hash — the hash keys WHAT is compiled, the dir is
        WHERE it is remembered.
    factor_cache_bytes: byte budget of the resident-factor pool
        (serve/factorcache.py — the chol_update / chol_downdate /
        posv_cached / blocktri_extend residency state).  NOT in the
        config hash, deliberately: residency is host-side runtime policy
        (which factors are remembered), the compiled bucket programs are
        keyed by shape alone — two engines differing only here share
        cache entries and a persistent dir on purpose, and a resizing
        never recompiles anything.
    """

    buckets: tuple[int, ...] = (256, 512, 1024)
    rows_buckets: tuple[int, ...] = (4096, 16384, 65536)
    nrhs_buckets: tuple[int, ...] = (1, 8, 64)
    nblocks_buckets: tuple[int, ...] = (8, 32, 64)
    block_buckets: tuple[int, ...] = (32, 64, 128)
    border_buckets: tuple[int, ...] = (8, 16, 32)
    blocktri_impl: str = "auto"
    blocktri_partitions: int = 0
    max_batch: int = 8
    max_delay_s: float = 0.005
    precision: Optional[str] = "highest"
    robust: Optional[RobustConfig] = None
    donate: Optional[bool] = None
    oversize: str = "models"
    small_n_impl: str = "auto"
    tail_fuse_depth: int = 0
    scheduler: str = "continuous"
    max_inflight: int = 2
    persist_dir: Optional[str] = None
    factor_cache_bytes: int = 256 << 20


class SolveEngine:
    """See module docstring.  One engine per (grid, ServeConfig); not
    thread-safe (a single dispatch loop owns it, like a jax program)."""

    def __init__(self, grid: Optional[Grid] = None,
                 cfg: ServeConfig = ServeConfig(), *,
                 validate: bool = False):
        if cfg.oversize not in ("models", "reject"):
            raise ValueError(f"unknown oversize policy {cfg.oversize!r}")
        if cfg.small_n_impl not in batched_small.IMPLS:
            raise ValueError(
                f"unknown small_n_impl {cfg.small_n_impl!r}: expected one "
                f"of {batched_small.IMPLS}"
            )
        if cfg.blocktri_impl not in blocktri.ALGORITHMS:
            raise ValueError(
                f"unknown blocktri_impl {cfg.blocktri_impl!r}: expected "
                f"one of {blocktri.ALGORITHMS}"
            )
        if cfg.blocktri_partitions < 0:
            raise ValueError(
                f"blocktri_partitions must be >= 0, got "
                f"{cfg.blocktri_partitions}"
            )
        if cfg.scheduler not in SCHEDULERS:
            raise ValueError(
                f"unknown scheduler {cfg.scheduler!r}: expected one of "
                f"{SCHEDULERS}"
            )
        if cfg.max_inflight < 1:
            raise ValueError(f"max_inflight must be >= 1, got "
                             f"{cfg.max_inflight}")
        self.grid = grid or Grid.square(c=1, devices=jax.devices()[:1])  # guarded-by: <frozen>
        self.cfg = cfg  # guarded-by: <frozen>
        # validate: run the lint donation-honored rule on every executable at
        # cache-insert time — a declared donate_argnums that XLA silently
        # drops (shape mismatch with every output) raises instead of leaving
        # the batch buffer double-resident for the cache entry's lifetime.
        self.validate = validate  # guarded-by: <frozen>
        self.stats = stats.Collector()  # guarded-by: <owner-thread>
        self.cache = ExecutableCache(cfg.persist_dir)  # guarded-by: <owner-thread>
        # host-side resident-factor pool (serve/factorcache.py): never part
        # of a traced program, so residency changes never recompile
        self.factors = FactorCache(cfg.factor_cache_bytes)  # guarded-by: <owner-thread>
        self.executor = Executor(cfg, self.grid, self.stats)  # guarded-by: <owner-thread>
        self.scheduler = Scheduler(cfg, self.executor, self._resolve_bucket)  # guarded-by: <owner-thread>
        # per-request span traces (obs/spans.py): every submit() starts a
        # RequestTrace; the serve path stamps it host-side as the request
        # moves.  Bounded (oldest dropped, counted) — emit_trace() exports
        # the run's chains as one serve:trace record.
        self.trace_log = spans.TraceLog()  # guarded-by: <owner-thread>
        # rolling-window live telemetry (serve/telemetry.py): None until
        # enable_telemetry() attaches an aggregator to the stats tap.
        self.telemetry = None  # guarded-by: <owner-thread>
        self._next_id = 0  # guarded-by: <owner-thread>
        # the device batched executables run on — staging target.  The
        # bucket programs are single-device (jit, no sharding); oversize
        # requests run the models/ schedules on the full grid.
        self._stage_device = self.grid.mesh.devices.ravel()[0]  # guarded-by: <frozen>
        # config-hash: everything that changes the compiled programs or the
        # padding geometry — two engines differing here must never share
        # cache entries, and the key makes that structural.  scheduler /
        # max_inflight / persist_dir are deliberately absent: they change
        # when and where programs run, never what was compiled.
        ident = repr((cfg.buckets, cfg.rows_buckets, cfg.nrhs_buckets,
                      cfg.nblocks_buckets, cfg.block_buckets,
                      cfg.border_buckets,
                      cfg.max_batch, cfg.precision, cfg.robust,
                      cfg.small_n_impl, cfg.tail_fuse_depth,
                      cfg.blocktri_impl, cfg.blocktri_partitions))
        self._cfg_hash = hashlib.sha1(ident.encode()).hexdigest()[:12]  # guarded-by: <frozen>
        self._grid_key = (self.grid.dx, self.grid.dy, self.grid.c,  # guarded-by: <frozen>
                          self.grid.platform)

    # ---- cache -------------------------------------------------------------

    def _small_route(self, bucket: batching.Bucket) -> bool:
        """Whether this bucket's executable runs the batched-grid small-N
        kernels — the same static-shape resolution api.batched('auto')
        makes at trace time, re-derived here so the stats collector can
        split small-bucket latency (latency_ms_small) from the rest."""
        impl = self.cfg.small_n_impl
        if impl == "vmap":
            return False
        # tiered buckets factor at the PLAN's dtype, not the request's —
        # a guaranteed f64 bucket factors in f32 and CAN take the
        # batched-grid kernels (the whole point of the tier); resolve
        # capability against what the compiled program actually factors in
        dtype = bucket.dtype
        if bucket.tier != "balanced":
            from capital_tpu.robust import refine

            dtype = str(refine.plan(bucket.tier, bucket.dtype).factor_dtype)
        if not batched_small.dtype_capable(dtype):
            # forced pallas included: api._batched_pallas falls back to the
            # vmap program for f64, so the executable is NOT small-route
            return False
        if bucket.op in ("posv_blocktri", "blocktri_extend",
                         "posv_arrowhead"):
            # the chain resolves through blocktri_small's own gate (per
            # scan step, not per bucket problem); impl mapping mirrors
            # api._batched_blocktri ('vmap'->xla handled above, forced
            # pallas variants below).  extend's scan step is the factor
            # step at k = b (no RHS rides the chain); the arrowhead's
            # widened chain solve runs at s + nrhs columns, which is
            # exactly the packed tail's column count.
            if impl in ("pallas", "pallas_split"):
                return True
            _, nblocks, b, _ = bucket.a_shape
            seg = blocktri.resolve_seg(nblocks)
            if bucket.op == "posv_blocktri":
                k = bucket.b_shape[2]
            elif bucket.op == "posv_arrowhead":
                k = bucket.b_shape[1]
            else:
                k = b
            return blocktri_small.default_impl(
                b, k, seg, dtype
            ) == "pallas"
        if bucket.op in ("chol_update", "chol_downdate"):
            if impl in ("pallas", "pallas_split"):
                return True
            return update_small.default_impl(
                bucket.a_shape[0], bucket.b_shape[1], bucket.dtype
            ) == "pallas"
        if impl in ("pallas", "pallas_split"):
            return True
        if bucket.op in ("posv_cached", "posv_cached_miss"):
            # potrs / potrf+potrs against posv's exact geometry — posv's
            # resolution is the right proxy (api's auto does the same)
            a_shape = (bucket.capacity,) + bucket.a_shape
            b_shape = (bucket.capacity,) + bucket.b_shape
            return batched_small.default_impl(
                "posv", a_shape, b_shape, dtype
            ) == "pallas"
        a_shape = (bucket.capacity,) + bucket.a_shape
        if bucket.op == "inv":
            # inv rides the posv kernel with an identity RHS (api.batched):
            # eligibility is posv's with b_shape == a_shape
            return batched_small.default_impl(
                "posv", a_shape, a_shape, dtype
            ) == "pallas"
        b_shape = ((bucket.capacity,) + bucket.b_shape
                   if bucket.b_shape is not None else None)
        return batched_small.default_impl(
            bucket.op, a_shape, b_shape, dtype
        ) == "pallas"

    def _blocktri_algorithm(self, nblocks: int, dtype) -> str:
        """Which chain algorithm a posv_blocktri bucket program runs —
        'scan' or 'partitioned' — re-derived from the same static
        resolution api._batched_blocktri makes at trace time, so the
        stats collector's impl split (serve-report's `blocktri` note)
        reflects the compiled reality, not the request."""
        if self.cfg.blocktri_impl == "partitioned":
            return blocktri.posv_algorithm(
                nblocks, dtype, impl="partitioned",
                partitions=self.cfg.blocktri_partitions)
        if self.cfg.blocktri_impl == "scan":
            return "scan"
        if self.cfg.small_n_impl != "auto":
            # a forced kernel flavor pins the sequential program under
            # blocktri_impl='auto' (api._batched_blocktri)
            return "scan"
        return blocktri.posv_algorithm(
            nblocks, dtype, partitions=self.cfg.blocktri_partitions)

    def _resolve_bucket(self, bucket: batching.Bucket) -> tuple:
        """The scheduler's get_exe callback: (executable, small_route)."""
        return self._get_batched(bucket), self._small_route(bucket)

    def _get_batched(self, bucket: batching.Bucket, warmup: bool = False):
        key = ("batch", bucket.key, self._grid_key, self._cfg_hash)
        dn = self.executor.donate_argnums(bucket)

        def build():
            dt = jnp.dtype(bucket.dtype)
            specs = [jax.ShapeDtypeStruct(
                (bucket.capacity,) + bucket.a_shape, dt)]
            if bucket.b_shape is not None:
                specs.append(jax.ShapeDtypeStruct(
                    (bucket.capacity,) + bucket.b_shape, dt))
            fn = api.batched(bucket.op, self.cfg.precision,
                             self.cfg.small_n_impl,
                             blocktri_impl=self.cfg.blocktri_impl,
                             blocktri_partitions=self.cfg.blocktri_partitions,
                             tier=bucket.tier)
            exe = jax.jit(fn, donate_argnums=dn).lower(*specs).compile()
            if self.validate and dn:
                from capital_tpu.lint import program as lint_program

                probs = lint_program.check_donation(
                    exe, dn, target=f"serve:{bucket.key}",
                )
                if probs:
                    raise AssertionError(
                        "donation dropped at cache insert: "
                        + "; ".join(f.message for f in probs)
                    )
            return exe

        return self.cache.get(key, build, warmup=warmup)

    def _get_single(self, op: str, a_sds, b_sds, warmup: bool = False):
        key = ("single", op, str(a_sds.dtype), a_sds.shape,
               b_sds.shape if b_sds is not None else None,
               self._grid_key, self._cfg_hash)

        def build():
            fn = api.single(op, self.grid, self.cfg.precision,
                            self.cfg.robust,
                            tail_fuse_depth=self.cfg.tail_fuse_depth)
            specs = (a_sds,) if b_sds is None else (a_sds, b_sds)
            return jax.jit(fn).lower(*specs).compile()

        return self.cache.get(key, build, warmup=warmup)

    def cache_stats(self) -> dict:
        """Hit/miss counters over request-driven executable lookups plus
        compile and persistent-tier counters (serve/cache.py).  warmup()
        compiles count separately — hit_rate measures steady-state
        traffic, and the acceptance gate is hit_rate == 1.0 after warmup;
        ``compiles`` is the cold-start gate (0 on a warm persistent
        dir)."""
        return self.cache.stats()

    def warmup(self, specs) -> int:
        """Pre-compile (or load from the persistent tier) executables for
        example request shapes.  `specs` is an iterable of (op, a_shape,
        b_shape, dtype) or (op, a_shape, b_shape, dtype, accuracy_tier) —
        b_shape None for inv, tier defaulting to 'balanced'.  Shapes
        resolve through the SAME bucket ladder as submit(), so warming one
        representative per bucket covers every shape that maps there;
        oversize shapes warm their exact-shape single route.  Returns the
        number of fresh compiles (0 when every entry loaded from a warm
        persist_dir)."""
        before = self.cache.warmup_compiles
        for op, a_shape, b_shape, dtype, *rest in specs:
            tier = rest[0] if rest else "balanced"
            dt = jnp.dtype(dtype)
            bucket = batching.bucket_for(
                op, tuple(a_shape), tuple(b_shape) if b_shape else None,
                str(dt), self.cfg, tier=tier,
            )
            if bucket is not None:
                self._get_batched(bucket, warmup=True)
            elif self.cfg.oversize == "models":
                a_sds = jax.ShapeDtypeStruct(tuple(a_shape), dt)
                b_sds = (jax.ShapeDtypeStruct(tuple(b_shape), dt)
                         if b_shape else None)
                self._get_single(op, a_sds, b_sds, warmup=True)
        return self.cache.warmup_compiles - before

    # ---- request path ------------------------------------------------------

    def submit(self, op: str, A, B=None, *,
               factor_token: Optional[str] = None,
               accuracy_tier: str = "balanced",
               deadline_ms: Optional[float] = None) -> Ticket:
        """Enqueue one solve request; returns a Ticket that resolves when
        its batch lands.  A capacity-full bucket DISPATCHES inside this
        call; under the continuous scheduler the dispatch is issued
        without waiting (the ticket is `done`, and `result()`/`pump()`/
        `drain()` land it).

        `accuracy_tier` makes precision a scheduling dimension
        (docs/SERVING.md "Accuracy tiers"): 'balanced' (default) runs the
        request dtype end-to-end; 'fast' factors one dtype DOWN
        (f64→f32, f32→bf16); 'guaranteed' factors in the fast dtype but
        iteratively refines the answer back to the request dtype's
        backward error (robust/refine), failing the request loudly if
        refinement does not converge.  Tiers bucket separately — the tier
        is part of the executable cache key — and are only defined for
        posv / lstsq / posv_blocktri.

        `factor_token` names a resident factor for the factor-residency
        ops (docs/SERVING.md "Factor residency"): chol_update /
        chol_downdate submit only the rank-k panel A = V (n, k) against
        the resident factor (loud failure when not resident — V alone
        cannot determine the answer); posv_cached submits the full
        (A, B) so a miss can seed the factor by refactoring; and
        blocktri_extend submits the appended chain packing
        A = (2, nblocks, b, b) — a never-seen token seeds a fresh chain
        (C[:, 0] zeroed host-side), an EVICTED token fails loudly (a
        silently re-seeded chain would be a wrong answer).

        `deadline_ms` is a per-request latency SLO (relative to submit
        entry).  It never changes scheduling today — it stamps the
        request's trace so the serve:trace record carries
        slack-at-dispatch and, on violation, which span ate the budget
        (docs/SERVING.md 'Deadlines and SLO attribution')."""
        t_enq = time.monotonic()
        tid = self._next_id
        self._next_id += 1
        ticket = Ticket(tid, t_enq)
        ticket.deadline_ms = (float(deadline_ms)
                              if deadline_ms is not None else None)
        if A is None and op != "session_close":
            raise ValueError(f"{op} requires an A operand")
        A = jnp.asarray(A) if A is not None else None
        B = jnp.asarray(B) if B is not None else None
        if op not in batching.OPS and op not in batching.SESSION_OPS:
            raise ValueError(
                f"unknown serve op {op!r}; expected one of "
                f"{batching.OPS + batching.SESSION_OPS}"
            )
        if accuracy_tier != "balanced" and op not in api.TIER_OPS:
            raise ValueError(
                f"accuracy_tier={accuracy_tier!r} is only defined for "
                f"{api.TIER_OPS}, got op {op!r}"
            )
        if op in batching.SESSION_OPS:
            if factor_token is None:
                raise ValueError(
                    f"{op} requires factor_token= (the session id — "
                    "docs/SERVING.md 'Streaming sessions')"
                )
            return self._submit_session(ticket, op, A, B,
                                        str(factor_token), accuracy_tier,
                                        t_enq)
        if op in batching.FACTOR_OPS:
            if factor_token is None:
                raise ValueError(
                    f"{op} requires factor_token= (docs/SERVING.md "
                    "'Factor residency')"
                )
            return self._submit_factor(ticket, op, A, B,
                                       str(factor_token), t_enq)
        if factor_token is not None:
            raise ValueError(
                f"factor_token is only valid for {batching.FACTOR_OPS}, "
                f"got op {op!r}"
            )
        if op == "posv_blocktri":
            if (A.ndim != 4 or A.shape[0] != 2
                    or A.shape[2] != A.shape[3]):
                raise ValueError(
                    f"posv_blocktri needs A = (2, nblocks, b, b) — "
                    f"[diagonal blocks, sub-diagonal blocks] — got "
                    f"{A.shape}"
                )
            if B is None or B.ndim != 3 or B.shape[:2] != A.shape[1:3]:
                raise ValueError(
                    f"posv_blocktri needs B = (nblocks, b, nrhs) riding "
                    f"A {A.shape}, got {None if B is None else B.shape}"
                )
        if op == "posv_arrowhead":
            if (A.ndim != 4 or A.shape[0] != 2
                    or A.shape[2] != A.shape[3]):
                raise ValueError(
                    f"posv_arrowhead needs A = (2, nblocks, b, b) — "
                    f"[diagonal blocks, sub-diagonal blocks], the "
                    f"posv_blocktri chain pack — got {A.shape}"
                )
            n_t = A.shape[1] * A.shape[2]
            if (B is None or B.ndim != 2 or B.shape[0] <= n_t
                    or B.shape[1] <= B.shape[0] - n_t):
                raise ValueError(
                    f"posv_arrowhead needs the packed tail B = "
                    f"(nblocks·b + s, s + nrhs) with s >= 1, nrhs >= 1 "
                    f"(models/arrowhead.pack) riding A {A.shape} "
                    f"(nblocks·b = {n_t}), got "
                    f"{None if B is None else B.shape}"
                )
        if op in ("posv", "lstsq") and (B is None or B.ndim != 2
                                        or B.shape[0] != A.shape[0]):
            raise ValueError(
                f"{op} needs a 2D RHS with {A.shape[0]} rows, got "
                f"{None if B is None else B.shape}"
            )
        if op in ("posv", "inv") and A.shape[0] != A.shape[1]:
            raise ValueError(f"{op} needs a square SPD operand, got {A.shape}")
        if op == "lstsq" and A.shape[0] < A.shape[1]:
            raise ValueError(f"lstsq expects tall input, got {A.shape}")
        # trace starts AFTER the raise-validation above: a rejected call
        # never entered the serve path, so no orphan chain may pollute
        # the 100%-complete trace gate
        self._start_trace(ticket, op, accuracy_tier)
        try:
            # HOST-side per-request fault tap on the concrete operand:
            # deterministic per submit() occurrence, and — critically —
            # never part of a traced program, so a fault corrupts exactly
            # one request and leaves the executable cache clean.
            A = faultinject.tap(A, point="serve::ingest")
        except faultinject.FaultInjected as e:
            self.executor.fail(ticket, op, str(e), t_enq)
            return ticket
        bucket = batching.bucket_for(
            op, A.shape, B.shape if B is not None else None,
            str(A.dtype), self.cfg, tier=accuracy_tier,
        )
        if bucket is None and accuracy_tier != "balanced":
            # the oversize models/ route has no tiered program — silently
            # serving a 'guaranteed' request at balanced precision (or a
            # 'fast' one at full) would betray the contract, so fail loud
            self.executor.fail(
                ticket, op,
                f"no bucket for {op} {A.shape}: accuracy_tier="
                f"{accuracy_tier!r} requests have no oversize route",
                t_enq,
            )
            return ticket
        if op in ("posv_blocktri", "posv_arrowhead"):
            # impl split: the bucketed program follows the engine's
            # algorithm knobs; the oversize single route runs posv's own
            # defaults (api.single), so it is counted that way.  The
            # arrowhead counts too — its widened chain solve runs the
            # same algorithm resolution (api._batched_arrowhead).
            self.stats.note_blocktri_impl(
                self._blocktri_algorithm(bucket.a_shape[1], bucket.dtype)
                if bucket is not None
                else blocktri.posv_algorithm(A.shape[1], A.dtype))
        if bucket is None:
            if self.cfg.oversize == "reject":
                self.executor.fail(
                    ticket, op,
                    f"no bucket for {op} {A.shape} and oversize='reject'",
                    t_enq,
                )
            else:
                self._run_single(ticket, op, A, B, t_enq)
            return ticket
        pa, pb = batching.pad_operands(op, A, B, bucket)
        if bucket.tier == "guaranteed":
            sink = self._refine_sink(op)
        elif op == "posv_arrowhead":
            sink = self._arrowhead_sink(tuple(A.shape), tuple(B.shape))
        else:
            sink = None
        self._admit(ticket, bucket, pa, pb, tuple(A.shape),
                    tuple(B.shape) if B is not None else None, t_enq,
                    sink=sink)
        return ticket

    def pump(self, now: Optional[float] = None) -> int:
        """Deadline flush + opportunistic landing: dispatch every bucket
        whose oldest request has aged past max_delay_s, and land every
        in-flight batch whose results are ready.  Call from the dispatch
        loop between submits; returns the number of batches flushed."""
        now = time.monotonic() if now is None else now
        return self.scheduler.pump(now)

    def drain(self) -> int:
        """Flush every non-empty queue regardless of age and land every
        in-flight batch (shutdown / test barrier).  Returns the number of
        batches flushed."""
        return self.scheduler.drain()

    def solve(self, op: str, A, B=None, *,
              factor_token: Optional[str] = None,
              accuracy_tier: str = "balanced",
              deadline_ms: Optional[float] = None) -> Response:
        """Convenience synchronous path: submit + drain + result."""
        ticket = self.submit(op, A, B, factor_token=factor_token,
                             accuracy_tier=accuracy_tier,
                             deadline_ms=deadline_ms)
        if not ticket.done:
            self.drain()
        return ticket.result()

    def queue_depth(self) -> int:
        return self.scheduler.queue_depth()

    def emit_stats(self, path: Optional[str] = None, **extra) -> dict:
        """Snapshot telemetry + cache counters into one serve:request_stats
        ledger record (appended to `path` when given)."""
        return self.stats.emit(
            path, grid=self.grid, config=self.cfg,
            cache=self.cache_stats(), factor_cache=self.factors.stats(),
            **extra,
        )

    def emit_trace(self, path: Optional[str] = None, *,
                   bubble_tol_ms: float = spans.DEFAULT_BUBBLE_TOL_MS,
                   **extra) -> dict:
        """Export the run's span chains as one serve:trace ledger record
        (appended to `path` when given) — the per-request counterpart of
        emit_stats()."""
        return self.trace_log.emit(
            path, grid=self.grid, config=self.cfg,
            bubble_tol_ms=bubble_tol_ms, **extra,
        )

    def enable_telemetry(self, window_s: float = 1.0, *,
                         sample_cap: Optional[int] = None):
        """Attach a rolling-window aggregator (serve/telemetry.py) to the
        stats tap: every request/batch/queue-depth note also lands in the
        current time window, and `self.telemetry.emit(path)` appends one
        serve:window record per closed window.  Host-side counters only —
        never part of the config hash, never a compiled program's
        concern.  Returns the aggregator."""
        from capital_tpu.serve import telemetry

        kw = {} if sample_cap is None else {"sample_cap": sample_cap}
        self.telemetry = telemetry.WindowAggregator(window_s, **kw)
        self.stats.window = self.telemetry
        return self.telemetry

    def _start_trace(self, ticket: Ticket, op: str,
                     tier: str) -> spans.RequestTrace:
        tr = self.trace_log.start(
            ticket.request_id, op, ticket.t_enq,
            deadline_ms=ticket.deadline_ms,
            tier=tier, cfg_hash=self._cfg_hash,
            replica_id=self.stats.replica_id,
        )
        ticket.trace = tr
        return tr

    # ---- factor residency (docs/SERVING.md "Factor residency") -------------

    def install_factor(self, token: str, R) -> list[str]:
        """Out-of-band seeding: install an upper-triangular R (A = RᵀR,
        the lapack.potrf uplo='U' convention) as the resident dense
        factor for `token`.  The serve-path seeding route is a
        posv_cached miss; this exists for clients that factored locally
        and want updates/solves without one priced miss.  Returns the
        tokens the byte budget evicted to make room."""
        R = jnp.asarray(R)
        if R.ndim != 2 or R.shape[0] != R.shape[1]:
            raise ValueError(
                f"install_factor needs a square (n, n) factor, got {R.shape}"
            )
        return self.factors.put(
            token, "dense", (R,),
            {"n": int(R.shape[0]), "dtype": str(R.dtype)},
        )

    def release_factor(self, token: str) -> bool:
        """Explicit client drop of a resident factor (clears any eviction
        tombstone — the token is free for honest reuse).  Returns whether
        an entry was resident."""
        return self.factors.release(token)

    def factor_stats(self) -> dict:
        """The FactorCache counter block (hits/misses/evictions/installs/
        released/downdate_degrades/bytes/hit_rate) — also emitted inside
        every serve:request_stats record once factor traffic exists."""
        return self.factors.stats()

    # ---- internals ---------------------------------------------------------

    def _admit(self, ticket: Ticket, bucket: batching.Bucket, pa, pb,
               a_shape, b_shape, t_enq: float, client_op=None,
               sink=None) -> None:
        """Stage + enqueue one padded request (the shared tail of submit
        and _submit_factor)."""
        if self.cfg.scheduler == "continuous":
            # async host->device staging AHEAD of dispatch: the transfer
            # overlaps whatever batch is currently executing, so by flush
            # time the operands are already device-resident (on-device
            # no-op when eager padding placed them there)
            with tracing.scope("SV::stage"):
                pa = jax.device_put(pa, self._stage_device)
                if pb is not None:
                    pb = jax.device_put(pb, self._stage_device)
        if ticket.trace is not None:
            # admit covers validation + fault tap + pad + stage; stamped
            # BEFORE scheduler.admit because a capacity flush dispatches
            # synchronously inside it (the enqueue span must start here)
            ticket.trace.tag(bucket=batching.bucket_label(bucket),
                             tier=bucket.tier)
            ticket.trace.extend("admit")
        self.scheduler.admit(bucket, _Pending(
            ticket, pa, pb, a_shape, b_shape, t_enq,
            client_op=client_op, sink=sink,
        ))
        self.stats.note_queue_depth(self.queue_depth())

    def _submit_factor(self, ticket: Ticket, op: str, A, B, token: str,
                       t_enq: float) -> Ticket:
        """The factor-residency submit path.  Residency resolves HERE,
        host-side, before padding or staging — the compiled bucket
        programs never see tokens, so residency changes never recompile
        anything.  Every not-servable case lands a LOUD failed Response,
        never a silent wrong answer: update/downdate against a
        non-resident token (V alone cannot determine the answer), any
        kind/shape/dtype mismatch with the resident entry, an extend
        against an EVICTED chain (a silently re-seeded identity chain
        would be a wrong answer), and oversize shapes regardless of
        cfg.oversize (the models/ paths have no residency to serve
        against)."""
        if op in ("chol_update", "chol_downdate"):
            if A.ndim != 2 or B is not None:
                raise ValueError(
                    f"{op} needs A = V (n, k), no B — the resident factor "
                    f"is the other operand; got A {A.shape}"
                    + ("" if B is None else f", B {B.shape}")
                )
        elif op == "posv_cached":
            if A.ndim != 2 or A.shape[0] != A.shape[1]:
                raise ValueError(
                    f"posv_cached needs a square SPD operand, got {A.shape}"
                )
            if B is None or B.ndim != 2 or B.shape[0] != A.shape[0]:
                raise ValueError(
                    f"posv_cached needs a 2D RHS with {A.shape[0]} rows, "
                    f"got {None if B is None else B.shape}"
                )
        else:  # blocktri_extend
            if A.ndim != 4 or A.shape[0] != 2 or A.shape[2] != A.shape[3]:
                raise ValueError(
                    f"blocktri_extend needs A = (2, nblocks, b, b) appended "
                    f"[diagonal, sub-diagonal] blocks, got {A.shape}"
                )
            if B is not None:
                raise ValueError(
                    f"blocktri_extend takes no B (the resident carry is "
                    f"the second operand), got B {B.shape}"
                )
        # same discipline as submit(): trace only once the request is past
        # the raise-validation and actually inside the serve path
        self._start_trace(ticket, op, "balanced")
        try:
            # same host-side per-request tap as submit(): a planted fault
            # corrupts exactly one request's operand and never bakes into
            # a cached executable OR a resident factor (sinks refuse to
            # install flagged results)
            A = faultinject.tap(A, point="serve::ingest")
        except faultinject.FaultInjected as e:
            self.executor.fail(ticket, op, str(e), t_enq)
            return ticket
        dt = str(A.dtype)
        ent = self.factors.lookup(token)

        def lose(msg: str) -> Ticket:
            self.executor.fail(
                ticket, op,
                msg + " (docs/SERVING.md 'Factor residency')", t_enq,
            )
            return ticket

        if op in ("chol_update", "chol_downdate"):
            if ent is None:
                why = ("evicted" if self.factors.evicted(token)
                       else "never seeded")
                return lose(
                    f"factor_token {token!r} not resident ({why}): {op} "
                    "ships only the rank-k panel V, so there is nothing to "
                    "update — seed with posv_cached or install_factor()"
                )
            if ent.kind != "dense":
                return lose(
                    f"factor_token {token!r} holds a {ent.kind} factor; "
                    f"{op} needs a dense one"
                )
            R = ent.arrays[0]
            n = int(R.shape[0])
            if A.shape[0] != n or str(R.dtype) != dt:
                return lose(
                    f"V {A.shape}/{dt} does not ride the resident factor "
                    f"({n}, {n})/{R.dtype} under token {token!r}"
                )
            bucket = batching.bucket_for(op, (n, n), tuple(A.shape), dt,
                                         self.cfg)
            if bucket is None:
                return lose(
                    f"no bucket for {op} n={n} k={A.shape[1]}: factor ops "
                    "have no oversize route"
                )
            pa, pb = batching.pad_operands(op, R, A, bucket)
            self._admit(
                ticket, bucket, pa, pb, (n, n), tuple(A.shape), t_enq,
                client_op=op, sink=self._update_sink(op, token, n, A),
            )
            return ticket

        if op == "posv_cached":
            n = int(A.shape[0])
            if ent is not None:
                if ent.kind != "dense":
                    return lose(
                        f"factor_token {token!r} holds a {ent.kind} "
                        "factor; posv_cached needs a dense one"
                    )
                R = ent.arrays[0]
                if int(R.shape[0]) != n or str(R.dtype) != dt:
                    return lose(
                        f"operand {A.shape}/{dt} does not match the "
                        f"resident factor {tuple(R.shape)}/{R.dtype} "
                        f"under token {token!r}"
                    )
                bucket = batching.bucket_for(
                    "posv_cached", (n, n), tuple(B.shape), dt, self.cfg)
                if bucket is None:
                    return lose(
                        f"no bucket for posv_cached n={n} "
                        f"nrhs={B.shape[1]}: factor ops have no oversize "
                        "route"
                    )
                pa, pb = batching.pad_operands("posv_cached", R, B, bucket)
                self._admit(ticket, bucket, pa, pb, (n, n),
                            tuple(B.shape), t_enq, client_op="posv_cached")
                return ticket
            # miss: seed by refactoring through the 3-output miss program
            # (X, R, info) — the full operand is on the wire, so re-seeding
            # is safe even for an evicted token (unlike extend, no hidden
            # state is lost); priced as a residency miss
            bucket = batching.bucket_for(
                "posv_cached_miss", tuple(A.shape), tuple(B.shape), dt,
                self.cfg)
            if bucket is None:
                return lose(
                    f"no bucket for posv_cached n={n} nrhs={B.shape[1]}: "
                    "factor ops have no oversize route"
                )
            pa, pb = batching.pad_operands("posv_cached_miss", A, B, bucket)
            self._admit(
                ticket, bucket, pa, pb, tuple(A.shape), tuple(B.shape),
                t_enq, client_op="posv_cached",
                sink=self._seed_sink(token, n),
            )
            return ticket

        # blocktri_extend
        nblocks, b = int(A.shape[1]), int(A.shape[2])
        if ent is not None:
            if ent.kind != "blocktri":
                return lose(
                    f"factor_token {token!r} holds a {ent.kind} factor; "
                    "blocktri_extend needs a blocktri chain"
                )
            if int(ent.meta["b"]) != b or ent.meta["dtype"] != dt:
                return lose(
                    f"appended blocks {A.shape}/{dt} do not ride the "
                    f"resident chain b={ent.meta['b']}/"
                    f"{ent.meta['dtype']} under token {token!r}"
                )
            carry = ent.arrays[2]
            prior = int(ent.meta["nblocks"])
        else:
            if self.factors.evicted(token):
                return lose(
                    f"factor_token {token!r} was EVICTED: extending a "
                    "silently re-seeded identity chain would be a wrong "
                    "answer — resubmit the full chain under a fresh token"
                )
            # fresh chain: identity carry + zeroed first coupling run the
            # SAME compiled program as a continuation (zero-recompile —
            # seed/continue is data, not a shape)
            carry = jnp.eye(b, dtype=A.dtype)
            A = A.at[1, 0].set(jnp.zeros((b, b), A.dtype))
            prior = 0
        bucket = batching.bucket_for(
            "blocktri_extend", tuple(A.shape), (b, b), dt, self.cfg)
        if bucket is None:
            return lose(
                f"no bucket for blocktri_extend nblocks={nblocks} b={b}: "
                "factor ops have no oversize route"
            )
        pa, pb = batching.pad_operands("blocktri_extend", A, carry, bucket)
        self._admit(
            ticket, bucket, pa, pb, tuple(A.shape), (b, b), t_enq,
            client_op="blocktri_extend",
            sink=self._extend_sink(token, b, prior),
        )
        return ticket

    # ---- streaming sessions (docs/SERVING.md "Streaming sessions") ---------

    def _submit_session(self, ticket: Ticket, op: str, A, B, token: str,
                        tier: str, t_enq: float) -> Ticket:
        """The session protocol submit path (serve/sessions.py drives it;
        the wire contract is engine-level so sessions are first-class serve
        ops, not a facade trick).  Residency resolves HERE, host-side,
        exactly like `_submit_factor` — the compiled bucket programs never
        see session ids, so session churn never recompiles anything.

        Wire shapes: session_open / session_append take the window blocks
        A = (2, nblocks, b, b) ([D; C] — C[:, 0] live for append, zeroed
        host-side for open) and no B; session_solve takes the CURRENT
        window A = (2, nblocks, b, b) plus B = (nblocks, b, nrhs) and the
        engine composes the 4-stack [D; C; L; Wt] from the resident
        factor; session_contract takes A = k (scalar — the number of
        oldest blocks to drop) and returns the NEW head diagonal factor
        block L_k (b, b) so the client can marginalize its window head
        (D[0] ← L_k·L_kᵀ, C[0] ← 0 — models/blocktri.contract docstring);
        session_close takes no operands and returns a 0/1 released flag.

        Loudness contract: any request against an EVICTED session fails
        with a tombstone-loud ``SessionEvicted`` error — the client must
        re-seed via session_open (which clears the tombstone); a request
        against a never-opened session fails as 'not open'.  Both are
        failed Responses, never silent identity answers."""
        if op in ("session_open", "session_append"):
            if (A.ndim != 4 or A.shape[0] != 2
                    or A.shape[2] != A.shape[3]):
                raise ValueError(
                    f"{op} needs A = (2, nblocks, b, b) window blocks "
                    f"[diagonal, sub-diagonal], got {A.shape}"
                )
            if B is not None:
                raise ValueError(
                    f"{op} takes no B (the carry is resident), got "
                    f"B {B.shape}"
                )
        elif op == "session_solve":
            if (A.ndim != 4 or A.shape[0] != 2
                    or A.shape[2] != A.shape[3]):
                raise ValueError(
                    f"session_solve needs A = (2, nblocks, b, b) — the "
                    f"session's current [D; C] window — got {A.shape}"
                )
            if B is None or B.ndim != 3 or B.shape[:2] != A.shape[1:3]:
                raise ValueError(
                    f"session_solve needs B = (nblocks, b, nrhs) riding "
                    f"A {A.shape}, got {None if B is None else B.shape}"
                )
        elif op == "session_contract":
            if A.ndim != 0:
                raise ValueError(
                    f"session_contract needs a scalar A = k (blocks to "
                    f"drop), got shape {A.shape}"
                )
            if B is not None:
                raise ValueError("session_contract takes no B")
        else:  # session_close
            if A is not None or B is not None:
                raise ValueError("session_close takes no operands")
        self._start_trace(ticket, op, tier)

        def lose(msg: str) -> Ticket:
            self.executor.fail(
                ticket, op,
                msg + " (docs/SERVING.md 'Streaming sessions')", t_enq,
            )
            return ticket

        def lose_missing() -> Ticket:
            if self.factors.evicted(token):
                return lose(
                    f"SessionEvicted: session {token!r} lost its resident "
                    "factor to cache pressure — re-seed the window with "
                    "session_open"
                )
            return lose(f"session {token!r} is not open")

        # host-side administrative ops: no compiled program, no device
        # flops — the span chain collapses to admit -> cache_lookup ->
        # respond under the 'session' trace kind
        if op == "session_close":
            if ticket.trace is not None:
                ticket.trace.kind = "session"
                ticket.trace.extend("admit")
            released = self.factors.release(token)
            if ticket.trace is not None:
                ticket.trace.extend("cache_lookup")
            return self._finish_host(
                ticket, op, jnp.int32(1 if released else 0), t_enq)
        if op == "session_contract":
            if ticket.trace is not None:
                ticket.trace.kind = "session"
                ticket.trace.extend("admit")
            ent = self.factors.lookup(token)
            if ticket.trace is not None:
                ticket.trace.extend("cache_lookup")
            if ent is None:
                return lose_missing()
            if ent.kind != "session":
                return lose(
                    f"factor_token {token!r} holds a {ent.kind} factor; "
                    "session ops need a session chain"
                )
            k = int(A)
            nblocks = int(ent.meta["nblocks"])
            if not 0 < k < nblocks:
                return lose(
                    f"session_contract k={k} must satisfy 0 < k < "
                    f"nblocks={nblocks} (contracting the whole chain is "
                    "session_close)"
                )
            L, Wt = ent.arrays[0], ent.arrays[1]
            Lc, Wtc = blocktri.contract(L[None], Wt[None], k)
            Lc, Wtc = Lc[0], Wtc[0]
            self.factors.put(
                token, "session", (Lc, Wtc, ent.arrays[2]),
                {"b": int(ent.meta["b"]), "nblocks": nblocks - k,
                 "dtype": ent.meta["dtype"],
                 "dropped": int(ent.meta.get("dropped", 0)) + k},
            )
            # the new head diagonal factor block: exactly what the client
            # needs to marginalize its window head (D[0] <- L_k·L_kᵀ)
            return self._finish_host(ticket, op, Lc[0], t_enq)

        try:
            A = faultinject.tap(A, point="serve::ingest")
        except faultinject.FaultInjected as e:
            self.executor.fail(ticket, op, str(e), t_enq)
            return ticket
        dt = str(A.dtype)

        if op == "session_open":
            nblocks, b = int(A.shape[1]), int(A.shape[2])
            # open IS the re-seed path: drop any prior incarnation and
            # clear an eviction tombstone — the one sanctioned way back
            # after a SessionEvicted failure
            self.factors.release(token)
            carry = jnp.eye(b, dtype=A.dtype)
            A = A.at[1, 0].set(jnp.zeros((b, b), A.dtype))
            bucket = batching.bucket_for(
                "session_extend", tuple(A.shape), (b, b), dt, self.cfg)
            if bucket is None:
                return lose(
                    f"no bucket for session window nblocks={nblocks} "
                    f"b={b}: session ops have no oversize route"
                )
            pa, pb = batching.pad_operands("session_extend", A, carry,
                                           bucket)
            self._admit(
                ticket, bucket, pa, pb, tuple(A.shape), (b, b), t_enq,
                client_op="session_open",
                sink=self._session_extend_sink(op, token, b),
            )
            return ticket

        ent = self.factors.lookup(token)
        if ent is None:
            return lose_missing()
        if ent.kind != "session":
            return lose(
                f"factor_token {token!r} holds a {ent.kind} factor; "
                "session ops need a session chain"
            )
        if int(ent.meta["b"]) != int(A.shape[2]) or ent.meta["dtype"] != dt:
            return lose(
                f"operand {A.shape}/{dt} does not ride the resident "
                f"session chain b={ent.meta['b']}/{ent.meta['dtype']} "
                f"under token {token!r}"
            )

        if op == "session_append":
            nblocks, b = int(A.shape[1]), int(A.shape[2])
            carry = ent.arrays[2]
            bucket = batching.bucket_for(
                "session_extend", tuple(A.shape), (b, b), dt, self.cfg)
            if bucket is None:
                return lose(
                    f"no bucket for session append nblocks={nblocks} "
                    f"b={b}: session ops have no oversize route"
                )
            pa, pb = batching.pad_operands("session_extend", A, carry,
                                           bucket)
            self._admit(
                ticket, bucket, pa, pb, tuple(A.shape), (b, b), t_enq,
                client_op="session_append",
                sink=self._session_extend_sink(op, token, b),
            )
            return ticket

        # session_solve
        nblocks, b = int(A.shape[1]), int(A.shape[2])
        if int(ent.meta["nblocks"]) != nblocks:
            return lose(
                f"session_solve window has {nblocks} blocks but the "
                f"resident chain under {token!r} has "
                f"{ent.meta['nblocks']} — the client window is out of "
                "sync (append/contract landed without updating it?)"
            )
        A4 = jnp.stack([A[0], A[1], ent.arrays[0], ent.arrays[1]])
        bucket = batching.bucket_for(
            "session_solve", tuple(A4.shape), tuple(B.shape), dt,
            self.cfg, tier=tier)
        if bucket is None:
            return lose(
                f"no bucket for session_solve nblocks={nblocks} b={b} "
                f"nrhs={B.shape[2]}: session ops have no oversize route"
            )
        pa, pb = batching.pad_operands("session_solve", A4, B, bucket)
        sink = (self._refine_sink("session_solve")
                if bucket.tier == "guaranteed" else None)
        self._admit(
            ticket, bucket, pa, pb, tuple(A4.shape), tuple(B.shape),
            t_enq, client_op="session_solve", sink=sink,
        )
        return ticket

    def _session_extend_sink(self, op: str, token: str, b: int):
        """Landing hook for session_open / session_append: install (open)
        or concatenate (append) the landed (L, Wt) blocks and roll the
        carry — `_extend_sink` with session bookkeeping.  Sessions are
        STATEFUL, so a flagged extend fails the request LOUDLY even under
        robust=None (the blocktri_extend path lets the engine's robust
        knob decide; a silently uninstalled session suffix would desync
        the client window from the resident chain forever)."""

        def sink(x, extras, raw_info):
            i = int(raw_info)
            if i != 0:
                return x, raw_info, (
                    f"{op} flagged breakdown (info={i}, segment-relative "
                    "to the submitted window blocks): the window is not "
                    f"SPD-consistent; resident session chain {token!r} "
                    "left unchanged" + (
                        " (open failed — the session is closed)"
                        if op == "session_open" else "")
                )
            L, Wt = x[0], x[1]
            dropped = 0
            ent = self.factors.peek(token)
            if ent is None and op != "session_open" \
                    and self.factors.evicted(token):
                # the resident chain was evicted between dispatch and
                # landing (the pool honored its byte budget mid-flight).
                # Installing only the new suffix would silently re-seed a
                # TRUNCATED chain — every later solve against it would be
                # wrong.  Fail loudly; "SessionEvicted:" is the tombstone
                # contract SessionManager._lose converts to the typed
                # SessionEvicted (misses == evicted_failures stays exact).
                return x, raw_info, (
                    f"SessionEvicted: resident chain {token!r} was evicted "
                    f"mid-flight (before this {op} landed); the suffix was "
                    "NOT installed — reopen the session and replay"
                )
            if ent is not None and ent.kind == "session":
                L = jnp.concatenate([ent.arrays[0], L], axis=0)
                Wt = jnp.concatenate([ent.arrays[1], Wt], axis=0)
                dropped = int(ent.meta.get("dropped", 0))
            self.factors.put(
                token, "session", (L, Wt, L[-1]),
                {"b": b, "nblocks": int(L.shape[0]),
                 "dtype": str(L.dtype), "dropped": dropped},
            )
            return x, raw_info, None

        return sink

    def _finish_host(self, ticket: Ticket, op: str, x, t_enq: float):
        """Land a host-side administrative session op (contract/close):
        no device dispatch happened, so there is no queue-wait/device
        split — latency is pure host bookkeeping."""
        t_land = time.monotonic()
        ticket.response = Response(
            request_id=ticket.request_id, op=op, ok=True, x=x, info=None,
            error=None, bucket=None, batched=False,
            latency_s=t_land - t_enq,
        )
        if ticket.trace is not None:
            ticket.trace.extend("respond")
            ticket.response.trace = ticket.trace
        self.stats.record_request(op, t_land - t_enq, ok=True)
        return ticket

    def _update_sink(self, op: str, token: str, n: int, V):
        """Landing hook for chol_update / chol_downdate: install R' on a
        clean info, refuse to install on breakdown.  A flagged DOWNDATE
        degrades to a fresh refactor S = RᵀR − VVᵀ from the still-resident
        OLD factor (put() only runs on success, so it was never
        overwritten) — the docs/ROBUSTNESS.md 'downdate failure'
        contract: degrade, and only if THAT also fails, fail loudly."""

        def sink(x, extras, raw_info):
            i = int(raw_info)
            if i == 0:
                self.factors.put(token, "dense", (x,),
                                 {"n": n, "dtype": str(x.dtype)})
                return x, raw_info, None
            if op == "chol_update":
                # a rank-k UPDATE of an SPD matrix cannot break down in
                # exact arithmetic — a flag here means a poisoned operand
                # (NaN/Inf V, e.g. an injected ingest fault).  No degrade
                # identity exists; refuse the result loudly and leave the
                # resident factor at its pre-update state.
                return x, raw_info, (
                    f"chol_update flagged breakdown (info={i}) — operand "
                    f"is not finite-SPD-consistent; resident factor "
                    f"{token!r} left unchanged"
                )
            ent = self.factors.peek(token)
            if ent is None:
                return x, raw_info, (
                    f"chol_downdate breakdown (info={i}) and token "
                    f"{token!r} was released/evicted mid-flight: no "
                    "resident state to degrade from"
                )
            self.factors.note_downdate_degrade()
            fn = self._get_degrade(n, int(V.shape[1]), str(V.dtype))
            R2, info2 = jax.block_until_ready(fn(ent.arrays[0], V))
            if int(info2) == 0:
                self.factors.put(token, "dense", (R2,),
                                 {"n": n, "dtype": str(R2.dtype)})
                return R2, RobustInfo(info=0, breakdown=1, shifted=0,
                                      sigma=0.0, escalated=1,
                                      ortho=-1.0), None
            return x, raw_info, (
                f"chol_downdate breakdown (info={i}) and the degrade "
                f"refactor ALSO failed (potrf info={int(info2)}): "
                "A − VVᵀ is not positive definite — resident factor "
                f"{token!r} left at its pre-downdate state"
            )

        return sink

    def _seed_sink(self, token: str, n: int):
        """Landing hook for the posv_cached miss program: install the
        freshly-refactored R (cropped from its padded batch slot) — but
        only on a clean info; a flagged refactor (operand not SPD) must
        never become resident truth."""

        def sink(x, extras, raw_info):
            if int(raw_info) == 0:
                R = extras[0][:n, :n]
                self.factors.put(token, "dense", (R,),
                                 {"n": n, "dtype": str(R.dtype)})
            return x, raw_info, None

        return sink

    def _extend_sink(self, token: str, b: int, prior: int):
        """Landing hook for blocktri_extend: append the new (L, Wt)
        blocks to the resident chain and roll the carry to the new last
        diagonal factor block.  A flagged extend installs nothing — the
        resident prefix stays valid (the chain is sequential; a failed
        suffix never corrupts it).  The landed info is SEGMENT-relative
        (offset 0) by design: offsetting inside the program would key a
        recompile per prefix length."""

        def sink(x, extras, raw_info):
            if int(raw_info) != 0:
                return x, raw_info, None
            L, Wt = x[0], x[1]
            ent = self.factors.peek(token)
            if ent is None and prior > 0 and self.factors.evicted(token):
                # the resident prefix was evicted between dispatch and
                # landing; installing only this suffix would re-seed a
                # chain missing its first `prior` blocks — silently wrong
                # for every later blocktri_solve.  Fail the extend loudly
                # (the tombstone stays, so retries fail too until the
                # client re-factors from scratch).
                return x, raw_info, (
                    f"resident blocktri chain {token!r} was evicted "
                    "mid-flight (before this extend landed); the suffix "
                    "was NOT installed — re-factor the full chain"
                )
            if ent is not None and ent.kind == "blocktri":
                L = jnp.concatenate([ent.arrays[0], L], axis=0)
                Wt = jnp.concatenate([ent.arrays[1], Wt], axis=0)
            self.factors.put(
                token, "blocktri", (L, Wt, L[-1]),
                {"b": b, "nblocks": int(L.shape[0]),
                 "dtype": str(L.dtype)},
            )
            return x, raw_info, None

        return sink

    def _arrowhead_sink(self, a_shape, b_shape):
        """Landing hook for posv_arrowhead: the 3-output bucket program
        (api._batched_arrowhead) lands the BLOCKED chain half through
        batching.crop with the padded corner half in the extras slot;
        crop the corner and concatenate the flat (nblocks·b + s, nrhs)
        response — the same layout the oversize single route returns, so
        clients see one contract on both routes."""
        nblocks, b = a_shape[1], a_shape[2]
        s = b_shape[0] - nblocks * b
        k = b_shape[1] - s

        def sink(x, extras, raw_info):
            flat = jnp.concatenate(
                [x.reshape(nblocks * b, k), extras[0][:s, :k]], axis=0)
            return flat, raw_info, None

        return sink

    def _refine_sink(self, op: str):
        """Landing hook for accuracy_tier='guaranteed' buckets: the tiered
        program (api._batched_refine) lands (X, iters, converged, resid)
        per request.  Record the measured refinement cost into the stats
        (sweep counts are data-dependent — they CANNOT be priced at trace
        time, which is why tracing only prices one sweep), and fail the
        request loudly when the refinement loop froze before reaching the
        correction-dtype backward-error tolerance: a 'guaranteed' answer
        that isn't is worse than an error."""

        def sink(x, extras, raw_info):
            it, conv, resid = (int(extras[0]), int(extras[1]),
                               float(extras[2]))
            self.stats.note_refine(it, bool(conv), resid)
            if not conv:
                return x, raw_info, (
                    f"accuracy_tier='guaranteed' {op} did not converge: "
                    f"refinement froze after {it} sweep(s) at backward "
                    f"error {resid:.3e} (stalled or diverging — the "
                    "operand is likely too ill-conditioned for the "
                    "factor dtype; resubmit at tier='balanced' in a "
                    "wider dtype)"
                )
            return x, raw_info, None

        return sink

    def _get_degrade(self, n: int, k: int, dtype: str):
        """The downdate-degrade program: refactor S = RᵀR − VVᵀ from
        scratch (lapack.potrf upper, with info).  Cached under the warmup
        counters on purpose — an exceptional-path compile must not read
        as a steady-state recompile in the zero-recompile gates."""
        key = ("degrade", n, k, dtype, self._grid_key, self._cfg_hash)

        def build():
            prec = self.cfg.precision

            def fn(R, V):
                with tracing.scope("UP::downdate"):
                    S = (jnp.einsum("ji,jk->ik", R, R, precision=prec)
                         - jnp.einsum("ik,jk->ij", V, V, precision=prec))
                    return lapack.potrf(S, uplo="U", with_info=True)

            dt = jnp.dtype(dtype)
            return jax.jit(fn).lower(
                jax.ShapeDtypeStruct((n, n), dt),
                jax.ShapeDtypeStruct((n, k), dt),
            ).compile()

        return self.cache.get(key, build, warmup=True)

    def _run_single(self, ticket: Ticket, op: str, A, B,
                    t_enq: float) -> None:
        tr = ticket.trace
        if tr is not None:
            # oversize singles never queue or batch: the chain collapses
            # to admit -> cache_lookup -> device -> respond
            tr.kind = "single"
            tr.extend("admit")
        a_sds = jax.ShapeDtypeStruct(A.shape, A.dtype)
        b_sds = (jax.ShapeDtypeStruct(B.shape, B.dtype)
                 if B is not None else None)
        exe = self._get_single(op, a_sds, b_sds)
        if tr is not None:
            tr.extend("cache_lookup")
        self.executor.run_single(ticket, op, A, B, exe, t_enq)
