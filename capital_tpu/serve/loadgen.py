"""Closed-loop load generator + SLO harness for the solve engine.

Closed-loop means each simulated client holds exactly one request in
flight: it submits, waits for the *landed* result, then submits the next.
Offered load is therefore `concurrency` outstanding requests, not a fixed
arrival rate — the honest way to measure a scheduler, because an open-loop
generator keeps offering work while the system backs up and turns a
throughput problem into an unbounded-queue artifact.

The harness drives one engine per scheduler mode over the SAME fixed-seed
workload and emits one `serve:request_stats` ledger record per mode, each
carrying a `loadgen` block (mode, concurrency, sustained QPS, wall time).
The continuous record also carries the sync baseline's QPS and the
speedup, so `obs serve-report` can gate the A/B result from the ledger
alone (`make serve-bench`):

* **throughput** — continuous vs sync QPS at equal occupancy (same
  workload, same ladder, same capacity ⇒ same batch shapes);
* **SLO split** — queue-wait vs on-device percentiles per mode: the
  overlap win shows up as queue-wait shrinking while device stays put;
* **zero steady-state recompiles** — each record's cache block
  (`misses == 0`, `hit_rate == 1.0` after warmup).

Everything here is host-side policy around `SolveEngine`'s public surface
(submit/pump/drain) — no jax in this module beyond what the engine does.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import numpy as np

from capital_tpu.serve.engine import ServeConfig, SolveEngine


@dataclasses.dataclass(frozen=True)
class Workload:
    """A reproducible request mix.  Shapes are drawn per-request from the
    ladders below with numpy's seeded Generator, so two runs (or two
    scheduler modes) see byte-identical operands in identical order.
    lstsq rows are 4*n — pick `ns` so that lands on the engine's
    rows_buckets ladder or the oversize path will dominate the measure."""

    requests: int = 200
    concurrency: int = 16
    seed: int = 0
    ops: tuple[str, ...] = ("posv", "lstsq")
    ns: tuple[int, ...] = (16, 32, 64)
    nrhs: tuple[int, ...] = (1, 4)
    dtype: str = "float32"


def build_requests(wl: Workload) -> list[tuple]:
    """Materialize the workload: a list of (op, A, B) with well-conditioned
    operands (SPD via G@G.T + n*I; tall G for lstsq)."""
    rng = np.random.default_rng(wl.seed)
    dt = np.dtype(wl.dtype)
    out = []
    for _ in range(wl.requests):
        op = wl.ops[int(rng.integers(len(wl.ops)))]
        n = int(wl.ns[int(rng.integers(len(wl.ns)))])
        k = int(wl.nrhs[int(rng.integers(len(wl.nrhs)))])
        if op == "lstsq":
            A = rng.standard_normal((4 * n, n)).astype(dt)
            B = rng.standard_normal((4 * n, k)).astype(dt)
        else:
            G = rng.standard_normal((n, n)).astype(dt)
            A = (G @ G.T + n * np.eye(n, dtype=dt)).astype(dt)
            B = (rng.standard_normal((n, k)).astype(dt)
                 if op == "posv" else None)
        out.append((op, A, B))
    return out


def warmup_specs(wl: Workload) -> list[tuple]:
    """One warmup spec per (op, n, nrhs) cell the workload can draw — after
    warmup(specs) every request hits the executable cache."""
    specs = []
    for op in wl.ops:
        for n in wl.ns:
            for k in wl.nrhs:
                if op == "lstsq":
                    specs.append((op, (4 * n, n), (4 * n, k), wl.dtype))
                elif op == "posv":
                    specs.append((op, (n, n), (n, k), wl.dtype))
                else:
                    specs.append((op, (n, n), None, wl.dtype))
    return specs


def run_closed_loop(eng: SolveEngine, requests: list[tuple],
                    concurrency: int) -> dict:
    """Drive one engine to completion over `requests` with at most
    `concurrency` clients outstanding.  A client's slot frees when its
    Response LANDS (not merely when its batch dispatches) — that is the
    closed loop.  Returns wall-clock QPS and completion counts."""
    todo = list(requests)
    todo.reverse()  # pop() from the tail preserves workload order
    outstanding: list = []
    completed = ok = failed = 0
    t_start = time.monotonic()
    while todo or outstanding:
        progressed = False
        while todo and len(outstanding) < concurrency:
            op, A, B = todo.pop()
            outstanding.append(eng.submit(op, A, B))
            progressed = True
        eng.pump()
        still = []
        for t in outstanding:
            if t.response is not None:
                completed += 1
                ok += 1 if t.response.ok else 0
                failed += 0 if t.response.ok else 1
                progressed = True
            else:
                still.append(t)
        outstanding = still
        if progressed:
            continue
        # nothing moved this iteration: force the oldest dispatched batch
        # to land, or (if everything is queued behind the flush deadline)
        # wait it out / drain the tail.
        dispatched = [t for t in outstanding if t.done]
        if dispatched:
            dispatched[0].result()
        elif eng.queue_depth() and todo:
            time.sleep(min(eng.cfg.max_delay_s, 1e-3))
        else:
            eng.drain()
    wall = time.monotonic() - t_start
    return {
        "requests": completed,
        "ok": ok,
        "failed": failed,
        "wall_s": round(wall, 6),
        "qps": round(completed / wall, 3) if wall > 0 else 0.0,
    }


def _mk_engine(cfg: ServeConfig, scheduler: str, grid=None) -> SolveEngine:
    return SolveEngine(grid, dataclasses.replace(cfg, scheduler=scheduler))


def compare(cfg: ServeConfig, wl: Workload = Workload(), *, grid=None,
            ledger_path: Optional[str] = None,
            modes: tuple[str, ...] = ("sync", "continuous")) -> dict:
    """The A/B harness: run the same workload through each scheduler mode
    (fresh engine each, shared ServeConfig otherwise — including
    persist_dir, which both may share safely), emit one ledger record per
    mode, and return {mode: results, 'speedup': continuous_qps/sync_qps}.

    The sync mode runs first so a cold persist_dir is warm for the
    continuous run in the same way a restart would see it; with warmup()
    covering the whole workload grid, both modes serve at misses == 0
    either way."""
    requests = build_requests(wl)
    specs = warmup_specs(wl)
    results: dict = {}
    records: dict = {}
    for mode in modes:
        eng = _mk_engine(cfg, mode, grid)
        eng.warmup(specs)
        results[mode] = run_closed_loop(eng, requests, wl.concurrency)
        results[mode]["cache"] = eng.cache_stats()
        records[mode] = (eng, results[mode])
    speedup = None
    if "sync" in results and "continuous" in results:
        base = results["sync"]["qps"]
        speedup = (round(results["continuous"]["qps"] / base, 4)
                   if base else None)
        results["speedup"] = speedup
    for mode, (eng, res) in records.items():
        block = {
            "mode": mode,
            "concurrency": wl.concurrency,
            "seed": wl.seed,
            "qps": res["qps"],
            "wall_s": res["wall_s"],
        }
        if mode == "continuous" and speedup is not None:
            block["baseline_qps"] = results["sync"]["qps"]
            block["speedup"] = speedup
        res["record"] = eng.emit_stats(ledger_path, loadgen=block)
    return results
