"""Closed-loop load generator + SLO harness for the solve engine.

Closed-loop means each simulated client holds exactly one request in
flight: it submits, waits for the *landed* result, then submits the next.
Offered load is therefore `concurrency` outstanding requests, not a fixed
arrival rate — the honest way to measure a scheduler, because an open-loop
generator keeps offering work while the system backs up and turns a
throughput problem into an unbounded-queue artifact.

The harness drives one engine per scheduler mode over the SAME fixed-seed
workload and emits one `serve:request_stats` ledger record per mode, each
carrying a `loadgen` block (mode, concurrency, sustained QPS, wall time).
The continuous record also carries the sync baseline's QPS and the
speedup, so `obs serve-report` can gate the A/B result from the ledger
alone (`make serve-bench`):

* **throughput** — continuous vs sync QPS at equal occupancy (same
  workload, same ladder, same capacity ⇒ same batch shapes);
* **SLO split** — queue-wait vs on-device percentiles per mode: the
  overlap win shows up as queue-wait shrinking while device stays put;
* **zero steady-state recompiles** — each record's cache block
  (`misses == 0`, `hit_rate == 1.0` after warmup).

Multi-replica (PR 9): `run_router_closed_loop` drives a serve.router
Router with M concurrent closed-loop clients — threads, or separate
client PROCESSES relaying submits over pipes (offered load that does not
share the router's GIL) — and `compare_replicas` is the replica-count A/B:
the same per-client offered load against 1 and N replicas sharing one
persist_dir, one aggregate record per count carrying a `router` block with
``baseline_qps`` and ``scaling_efficiency = (qps_N / N) / (qps_1 / 1)`` —
the honest scaling number (raw speedup flatters N replicas on any rig;
efficiency reads 1.0 only when each replica pulls its weight).

Everything here is host-side policy around the engine/router public
surfaces (submit/pump/drain) — the engine import is lazy so a spawned
client process never imports jax at all.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class Workload:
    """A reproducible request mix.  Shapes are drawn per-request from the
    ladders below with numpy's seeded Generator, so two runs (or two
    scheduler modes) see byte-identical operands in identical order.
    lstsq rows are 4*n — pick `ns` so that lands on the engine's
    rows_buckets ladder or the oversize path will dominate the measure."""

    requests: int = 200
    concurrency: int = 16
    seed: int = 0
    ops: tuple[str, ...] = ("posv", "lstsq")
    ns: tuple[int, ...] = (16, 32, 64)
    nrhs: tuple[int, ...] = (1, 4)
    dtype: str = "float32"
    #: per-request latency SLO stamped onto every submit (None = no SLO):
    #: the serve:trace record then carries slack-at-dispatch and
    #: violation attribution per request
    deadline_ms: Optional[float] = None


def build_requests(wl: Workload) -> list[tuple]:
    """Materialize the workload: a list of (op, A, B) with well-conditioned
    operands (SPD via G@G.T + n*I; tall G for lstsq)."""
    rng = np.random.default_rng(wl.seed)
    dt = np.dtype(wl.dtype)
    out = []
    for _ in range(wl.requests):
        op = wl.ops[int(rng.integers(len(wl.ops)))]
        n = int(wl.ns[int(rng.integers(len(wl.ns)))])
        k = int(wl.nrhs[int(rng.integers(len(wl.nrhs)))])
        if op == "lstsq":
            A = rng.standard_normal((4 * n, n)).astype(dt)
            B = rng.standard_normal((4 * n, k)).astype(dt)
        else:
            G = rng.standard_normal((n, n)).astype(dt)
            A = (G @ G.T + n * np.eye(n, dtype=dt)).astype(dt)
            B = (rng.standard_normal((n, k)).astype(dt)
                 if op == "posv" else None)
        out.append((op, A, B))
    return out


def warmup_specs(wl: Workload) -> list[tuple]:
    """One warmup spec per (op, n, nrhs) cell the workload can draw — after
    warmup(specs) every request hits the executable cache."""
    specs = []
    for op in wl.ops:
        for n in wl.ns:
            for k in wl.nrhs:
                if op == "lstsq":
                    specs.append((op, (4 * n, n), (4 * n, k), wl.dtype))
                elif op == "posv":
                    specs.append((op, (n, n), (n, k), wl.dtype))
                else:
                    specs.append((op, (n, n), None, wl.dtype))
    return specs


def run_closed_loop(eng, requests: list[tuple], concurrency: int,
                    deadline_ms: Optional[float] = None) -> dict:
    """Drive one engine to completion over `requests` with at most
    `concurrency` clients outstanding.  A client's slot frees when its
    Response LANDS (not merely when its batch dispatches) — that is the
    closed loop.  `deadline_ms` stamps the per-request SLO onto every
    submit (trace attribution; scheduling is unchanged).  Returns
    wall-clock QPS and completion counts."""
    todo = list(requests)
    todo.reverse()  # pop() from the tail preserves workload order
    outstanding: list = []
    completed = ok = failed = 0
    t_start = time.monotonic()
    while todo or outstanding:
        progressed = False
        while todo and len(outstanding) < concurrency:
            op, A, B = todo.pop()
            outstanding.append(eng.submit(op, A, B,
                                          deadline_ms=deadline_ms))
            progressed = True
        eng.pump()
        still = []
        for t in outstanding:
            if t.response is not None:
                completed += 1
                ok += 1 if t.response.ok else 0
                failed += 0 if t.response.ok else 1
                progressed = True
            else:
                still.append(t)
        outstanding = still
        if progressed:
            continue
        # nothing moved this iteration: force the oldest dispatched batch
        # to land, or (if everything is queued behind the flush deadline)
        # wait it out / drain the tail.
        dispatched = [t for t in outstanding if t.done]
        if dispatched:
            dispatched[0].result()
        elif eng.queue_depth() and todo:
            time.sleep(min(eng.cfg.max_delay_s, 1e-3))
        else:
            eng.drain()
    wall = time.monotonic() - t_start
    return {
        "requests": completed,
        "ok": ok,
        "failed": failed,
        "wall_s": round(wall, 6),
        "qps": round(completed / wall, 3) if wall > 0 else 0.0,
    }


def _mk_engine(cfg, scheduler: str, grid=None):
    from capital_tpu.serve.engine import SolveEngine

    return SolveEngine(grid, dataclasses.replace(cfg, scheduler=scheduler))


def compare(cfg: ServeConfig, wl: Workload = Workload(), *, grid=None,
            ledger_path: Optional[str] = None,
            modes: tuple[str, ...] = ("sync", "continuous"),
            window_s: Optional[float] = None,
            trace: bool = False) -> dict:
    """The A/B harness: run the same workload through each scheduler mode
    (fresh engine each, shared ServeConfig otherwise — including
    persist_dir, which both may share safely), emit one ledger record per
    mode, and return {mode: results, 'speedup': continuous_qps/sync_qps}.

    `window_s` attaches rolling-window telemetry to each mode's engine
    and appends one serve:window record per closed window; `trace`
    appends one serve:trace record per mode carrying every request's
    span chain.  Both default off, so pre-existing ledger contents stay
    byte-compatible.

    The sync mode runs first so a cold persist_dir is warm for the
    continuous run in the same way a restart would see it; with warmup()
    covering the whole workload grid, both modes serve at misses == 0
    either way."""
    requests = build_requests(wl)
    specs = warmup_specs(wl)
    results: dict = {}
    records: dict = {}
    for mode in modes:
        eng = _mk_engine(cfg, mode, grid)
        if window_s:
            eng.enable_telemetry(window_s)
        eng.warmup(specs)
        results[mode] = run_closed_loop(eng, requests, wl.concurrency,
                                        deadline_ms=wl.deadline_ms)
        results[mode]["cache"] = eng.cache_stats()
        records[mode] = (eng, results[mode])
    speedup = None
    if "sync" in results and "continuous" in results:
        base = results["sync"]["qps"]
        speedup = (round(results["continuous"]["qps"] / base, 4)
                   if base else None)
        results["speedup"] = speedup
    for mode, (eng, res) in records.items():
        block = {
            "mode": mode,
            "concurrency": wl.concurrency,
            "seed": wl.seed,
            "qps": res["qps"],
            "wall_s": res["wall_s"],
        }
        if mode == "continuous" and speedup is not None:
            block["baseline_qps"] = results["sync"]["qps"]
            block["speedup"] = speedup
        res["record"] = eng.emit_stats(ledger_path, loadgen=block)
        if eng.telemetry is not None:
            wrecs = eng.telemetry.emit(ledger_path, grid=eng.grid,
                                       config=eng.cfg,
                                       loadgen={"mode": mode})
            res["window_records"] = len(wrecs)
        if trace:
            res["trace_record"] = eng.emit_trace(
                ledger_path, loadgen={"mode": mode})
    return results


# ---- multi-replica offered load (PR 9; docs/SERVING.md) -------------------


def _client_requests(wl: Workload, client: int, clients: int) -> list[tuple]:
    """Client `client`'s slice of the workload: one shared fixed-seed list,
    dealt round-robin — every client sees the same op/bucket mix, and the
    union across clients is byte-identical for every (replica count,
    client mode) being compared."""
    return build_requests(wl)[client::clients]


def _client_loop(submit, requests: list[tuple]) -> dict:
    """One closed-loop client: exactly one request in flight.  `submit` is
    op, A, B -> (ok, error); counts come back to the caller."""
    ok = failed = 0
    for op, A, B in requests:
        good, _err = submit(op, A, B)
        ok += 1 if good else 0
        failed += 0 if good else 1
    return {"requests": len(requests), "ok": ok, "failed": failed}


def _client_proc_main(conn, wl: Workload, client: int, clients: int) -> None:
    """Child main for one PROCESS client (spawn target — top level, and
    this module imports no jax, so the client process stays lightweight).
    Speaks ("submit", seq, op, A, B) / receives ("result", seq, ok, error);
    strictly one in flight — the closed loop lives HERE, in the client."""
    reqs = _client_requests(wl, client, clients)
    seq = 0

    def submit(op, A, B):
        nonlocal seq
        conn.send(("submit", seq, op, A, B))
        kind, rseq, good, err = conn.recv()
        assert kind == "result" and rseq == seq, (kind, rseq, seq)
        seq += 1
        return good, err

    counts = _client_loop(submit, reqs)
    conn.send(("done", counts))
    conn.close()


def _run_thread_clients(router, wl: Workload, clients: int,
                        timeout: float) -> list[dict]:
    import threading

    out: list[Optional[dict]] = [None] * clients

    def client(ci: int) -> None:
        def submit(op, A, B):
            t = router.submit(op, A, B)
            res = t.result(timeout)
            return res.ok, res.error

        out[ci] = _client_loop(submit, _client_requests(wl, ci, clients))

    threads = [threading.Thread(target=client, args=(ci,), daemon=True)
               for ci in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
        if t.is_alive():
            raise TimeoutError(f"loadgen client thread {t.name} wedged")
    return [c for c in out if c is not None]


def _run_process_clients(router, wl: Workload, clients: int,
                         timeout: float) -> list[dict]:
    """M client processes against the in-process router: each child runs
    its own closed loop over a pipe; this frontend relays submits to the
    router and landed results back.  The router's pump thread is already
    running — this loop only moves messages."""
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    conns, procs = [], []
    for ci in range(clients):
        parent, child = ctx.Pipe(duplex=True)
        p = ctx.Process(target=_client_proc_main,
                        args=(child, wl, ci, clients), daemon=True)
        p.start()
        child.close()
        conns.append(parent)
        procs.append(p)
    pending: dict[tuple[int, int], object] = {}
    counts: dict[int, dict] = {}
    deadline = time.monotonic() + timeout
    try:
        while len(counts) < clients:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"process-client loadgen incomplete: {len(counts)}/"
                    f"{clients} clients done, {len(pending)} in flight"
                )
            progressed = False
            for ci, conn in enumerate(conns):
                if ci in counts:
                    continue
                while conn.poll(0):
                    try:
                        msg = conn.recv()
                    except EOFError:
                        raise RuntimeError(
                            f"loadgen client process {ci} died mid-run"
                        ) from None
                    if msg[0] == "submit":
                        _, seq, op, A, B = msg
                        pending[(ci, seq)] = router.submit(op, A, B)
                        progressed = True
                    else:  # ("done", counts) — stop polling this pipe;
                        # the child closes its end next and poll() would
                        # report the EOF as readable forever
                        counts[ci] = msg[1]
                        progressed = True
                        break
            for key, t in list(pending.items()):
                if t.done:
                    ci, seq = key
                    res = t.response
                    conns[ci].send(("result", seq, res.ok, res.error))
                    del pending[key]
                    progressed = True
            if not progressed:
                time.sleep(1e-3)
    finally:
        for p in procs:
            p.join(5.0)
            if p.is_alive():
                p.kill()
    return [counts[ci] for ci in sorted(counts)]


def run_router_closed_loop(router, wl: Workload, clients: int, *,
                           client_mode: str = "thread",
                           timeout: float = 600.0) -> dict:
    """Drive one Router to completion with `clients` closed-loop clients
    (each holds exactly one request in flight — offered load is `clients`
    outstanding).  `client_mode="process"` puts each client in its own
    spawned process (loads the router through real IPC and leaves the GIL
    to the router+replicas).  The router must have its replicas registered
    and warmed; its pump thread is started (and left running) here."""
    if client_mode not in ("thread", "process"):
        raise ValueError(f"unknown client_mode {client_mode!r}")
    router.start()
    t_start = time.monotonic()
    runner = (_run_thread_clients if client_mode == "thread"
              else _run_process_clients)
    per_client = runner(router, wl, clients, timeout)
    wall = time.monotonic() - t_start
    completed = sum(c["requests"] for c in per_client)
    return {
        "requests": completed,
        "ok": sum(c["ok"] for c in per_client),
        "failed": sum(c["failed"] for c in per_client),
        "clients": clients,
        "client_mode": client_mode,
        "wall_s": round(wall, 6),
        "qps": round(completed / wall, 3) if wall > 0 else 0.0,
    }


def compare_replicas(
    cfg, wl: Workload = Workload(), *,
    replica_counts: tuple[int, ...] = (1, 2),
    replica_mode: str = "thread",
    client_mode: str = "thread",
    policy: str = "least_loaded",
    ledger_path: Optional[str] = None,
    env: Optional[dict] = None,
) -> dict:
    """The replica-count A/B: the same fixed-seed workload at EQUAL
    per-client offered load (clients and total requests both scale with
    the replica count, so each client's closed loop is identical across
    counts) against a fresh router per count, all counts sharing
    ``cfg.persist_dir`` — count 1 warms the disk tier, every later count
    proves the multi-writer warm path.

    Emits per-replica records plus one aggregate record per count; the
    aggregate's `router` block carries qps, and — for counts past the
    first — ``baseline_qps`` (the first count's) and
    ``scaling_efficiency = (qps_N / N) / (qps_base / base)``.  Returns
    {count: results, 'scaling_efficiency': ..., 'speedup': ...}."""
    from capital_tpu.serve.replica import make_replica
    from capital_tpu.serve.router import Router, RouterConfig

    specs = warmup_specs(wl)
    results: dict = {}
    base_n = replica_counts[0]
    for n in replica_counts:
        wl_n = dataclasses.replace(wl, requests=wl.requests * n)
        clients = wl.concurrency * n
        router = Router(RouterConfig(policy=policy))
        for i in range(n):
            router.add_replica(make_replica(
                replica_mode, f"n{n}-r{i}", cfg, env=env))
        warm = router.warmup(specs)
        try:
            res = run_router_closed_loop(
                router, wl_n, clients, client_mode=client_mode)
            res["warmup_fresh"] = warm
            res["counters"] = router.counters()
            block = {
                "replicas": n,
                "policy": policy,
                "replica_mode": replica_mode,
                "client_mode": client_mode,
                "clients": clients,
                "seed": wl.seed,
                "qps": res["qps"],
                "wall_s": res["wall_s"],
            }
            if n != base_n and base_n in results:
                base_qps = results[base_n]["qps"]
                block["baseline_qps"] = base_qps
                block["baseline_replicas"] = base_n
                if base_qps:
                    block["speedup"] = round(res["qps"] / base_qps, 4)
                    block["scaling_efficiency"] = round(
                        (res["qps"] / n) / (base_qps / base_n), 4)
            res["router_block"] = block
            res["records"] = router.emit_stats(ledger_path, router=block)
            results[n] = res
        finally:
            router.stop()
    counts = [n for n in replica_counts if n in results]
    if len(counts) >= 2:
        last = results[counts[-1]]["router_block"]
        results["speedup"] = last.get("speedup")
        results["scaling_efficiency"] = last.get("scaling_efficiency")
    return results
