"""CLI: ``python -m capital_tpu.serve smoke ...`` — the serving self-check.

Runs a small mixed-bucket workload on the local platform (CPU in CI),
writes one serve:request_stats ledger record, and gates on the two
acceptance properties of docs/SERVING.md:

* **zero recompiles**: after warmup over the workload's >= 3 shape
  buckets, every request-driven executable lookup must hit
  (cache misses == 0, hit_rate == 1.0);
* **numerics**: the max per-request residual stays under the pinned
  dtype gate (bench/drivers._tolerance; the lstsq normal-equation
  residual gets the same 10x allowance the qr drivers use — the gram
  squares the conditioning).

With ``--persist-dir`` the smoke exercises the persistent AOT tier, and
``--max-compiles 0`` turns it into the cold-start proof: a SECOND smoke
pointed at the same (now warm) directory must serve the whole workload
with zero fresh XLA compiles — every executable deserializes from disk.
`make serve-smoke` runs exactly that pair, then gates the ledger with
``obs serve-report``.

``python -m capital_tpu.serve loadgen`` is the closed-loop A/B harness
(serve/loadgen.py): the same fixed-seed workload through the sync (PR 4
stop-and-go) and continuous schedulers, one serve:request_stats record
per mode with the queue-wait/device split and the QPS comparison —
`make serve-bench` gates those records via ``obs serve-report``.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax


def _workload(requests: int, seed: int):
    """Deterministic mixed workload touching >= 3 n-buckets, all three ops,
    and two nrhs buckets."""
    import numpy as np

    rng = np.random.default_rng(seed)
    ns = (12, 24, 48, 16, 30, 64)  # -> buckets 16 / 32 / 64
    ks = (1, 3)  # -> nrhs buckets 1 / 4
    # 5-long op cycle against the 6-long n cycle (coprime) so blocks sweep
    # the bucket grid; requests arrive in blocks of 4 IDENTICAL shapes
    # (j = i // 4) so the capacity flush path sees full batches, while the
    # pump() cadence below (every 7 submissions, coprime with 4) still
    # catches partial blocks on the deadline path
    ops = ("posv", "inv", "lstsq", "posv", "lstsq")
    out = []
    for i in range(requests):
        j = i // 4
        op = ops[j % len(ops)]
        n = ns[j % len(ns)]
        k = ks[j % len(ks)]
        if op == "lstsq":
            m = 4 * n
            A = rng.standard_normal((m, n))
            B = rng.standard_normal((m, k))
        else:
            M = rng.standard_normal((n, n))
            A = M @ M.T / n + 3.0 * np.eye(n)
            B = rng.standard_normal((n, k)) if op == "posv" else None
        out.append((op, A, B))
    return out


def _residual(op: str, A, B, x) -> float:
    import numpy as np

    A = np.asarray(A, dtype=np.float64)
    x = np.asarray(x, dtype=np.float64)
    if op == "inv":
        n = A.shape[0]
        return float(np.linalg.norm(A @ x - np.eye(n)) / np.sqrt(n))
    B = np.asarray(B, dtype=np.float64)
    if op == "posv":
        return float(np.linalg.norm(A @ x - B) / np.linalg.norm(B))
    r = A.T @ (A @ x - B)
    return float(np.linalg.norm(r) / np.linalg.norm(A.T @ B))


def _smoke(args) -> int:
    import jax.numpy as jnp

    from capital_tpu.bench.drivers import _tolerance
    from capital_tpu.serve import ServeConfig, SolveEngine

    dtype = jnp.dtype(args.dtype)
    cfg = ServeConfig(
        buckets=(16, 32, 64),
        rows_buckets=(64, 128, 256),
        nrhs_buckets=(1, 4),
        max_batch=4,
        max_delay_s=0.01,
        # every smoke bucket is <= batched_small.SMALL_N_MAX, so 'auto'
        # routes the posv/lstsq buckets through the fused batched-grid
        # kernels (interpret mode on CPU) — the smoke exercises the same
        # dispatch a TPU deployment gets, and latency_ms_small lands in
        # the record for the --max-p99-ms-small serve-report gate.
        small_n_impl=args.small_n_impl,
        scheduler=args.scheduler,
        persist_dir=args.persist_dir,
    )
    eng = SolveEngine(cfg=cfg)
    work = _workload(args.requests, args.seed)
    compiles = eng.warmup(
        (op, A.shape, B.shape if B is not None else None, dtype)
        for op, A, B in work
    )
    print(f"# serve-smoke: warmup compiled {compiles} executables")

    tickets = []
    for i, (op, A, B) in enumerate(work):
        A = jnp.asarray(A, dtype=dtype)
        B = jnp.asarray(B, dtype=dtype) if B is not None else None
        tickets.append(eng.submit(op, A, B))
        if i % 7 == 6:
            # let the oldest queue age past the deadline so the max-delay
            # flush path runs in the smoke, not only the capacity path
            time.sleep(cfg.max_delay_s)
            eng.pump()
    eng.drain()

    failures = []
    tol = _tolerance(dtype)
    worst: dict[str, float] = {}
    buckets_seen = set()
    for (op, A, B), t in zip(work, tickets):
        r = t.result()
        if not r.ok or r.x is None:
            failures.append(f"request {r.request_id} ({op}) failed: {r.error}")
            continue
        if r.bucket is not None:
            buckets_seen.add(r.bucket[:3])  # (op, dtype, a_shape)
        res = _residual(op, A, B, r.x)
        worst[op] = max(worst.get(op, 0.0), res)
        gate = 10 * tol if op == "lstsq" else tol
        if res >= gate:
            failures.append(
                f"request {r.request_id} ({op} {A.shape}) residual "
                f"{res:.3e} >= {gate:.0e}"
            )
    cache = eng.cache_stats()
    n_buckets = len({b[2] for b in buckets_seen})
    rec = eng.emit_stats(
        args.ledger,
        smoke={
            "max_residual": {k: round(v, 12) for k, v in worst.items()},
            "distinct_bucket_shapes": n_buckets,
            "residual_tol": tol,
        },
    )
    print(json.dumps(rec["request_stats"]))
    for op, v in sorted(worst.items()):
        print(f"# serve-smoke: max {op} residual {v:.3e}")
    if n_buckets < 3:
        failures.append(
            f"workload touched only {n_buckets} bucket shapes (< 3)"
        )
    if cache["misses"] or not cache["hits"]:
        failures.append(
            f"steady-state recompile: cache {cache} (expected misses == 0 "
            "after warmup)"
        )
    if args.max_compiles is not None and cache["compiles"] > args.max_compiles:
        disk = cache.get("disk", {})
        failures.append(
            f"cold-start gate: {cache['compiles']} fresh XLA compiles > "
            f"--max-compiles {args.max_compiles} (disk tier: {disk}) — the "
            "persistent cache did not cover the workload"
        )
    for f in failures:
        print(f"# serve-smoke FAIL: {f}", file=sys.stderr)
    if failures:
        return 1
    print(
        f"# serve-smoke OK: {len(tickets)} requests, hit_rate "
        f"{cache['hit_rate']:.2f} over {cache['hits']} lookups, "
        f"{n_buckets} bucket shapes, {cache['compiles']} fresh compiles"
    )
    return 0


def _loadgen(args) -> int:
    from capital_tpu.serve import loadgen
    from capital_tpu.serve.engine import ServeConfig

    cfg = ServeConfig(
        buckets=(16, 32, 64),
        rows_buckets=(64, 128, 256),
        nrhs_buckets=(1, 4),
        max_batch=4,
        max_delay_s=0.002,
        small_n_impl=args.small_n_impl,
        max_inflight=args.max_inflight,
        persist_dir=args.persist_dir,
    )
    wl = loadgen.Workload(
        requests=args.requests, concurrency=args.concurrency,
        seed=args.seed, dtype=args.dtype,
    )
    results = loadgen.compare(cfg, wl, ledger_path=args.ledger)
    failures = []
    for mode in ("sync", "continuous"):
        res = results.get(mode)
        if res is None:
            continue
        cache = res["cache"]
        print(
            f"# serve-loadgen {mode}: {res['requests']} requests in "
            f"{res['wall_s']:.3f}s = {res['qps']:.1f} qps "
            f"(concurrency {wl.concurrency}, cache misses "
            f"{cache['misses']}, compiles {cache['compiles']})"
        )
        if res["failed"]:
            failures.append(f"{mode}: {res['failed']} requests failed")
        if cache["misses"]:
            failures.append(
                f"{mode}: {cache['misses']} steady-state recompiles "
                "(warmup must cover the workload grid)"
            )
    if results.get("speedup") is not None:
        print(f"# serve-loadgen: continuous/sync speedup "
              f"{results['speedup']:.2f}x")
        if args.min_speedup is not None and results["speedup"] < args.min_speedup:
            failures.append(
                f"speedup {results['speedup']:.2f}x < --min-speedup "
                f"{args.min_speedup}"
            )
    for f in failures:
        print(f"# serve-loadgen FAIL: {f}", file=sys.stderr)
    if failures:
        return 1
    print("# serve-loadgen OK")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="capital_tpu.serve")
    sub = p.add_subparsers(dest="cmd", required=True)
    s = sub.add_parser("smoke", help="mixed-bucket serving self-check")
    s.add_argument("--requests", type=int, default=50)
    s.add_argument("--dtype", default="float32")
    s.add_argument("--seed", type=int, default=0)
    s.add_argument("--ledger", default=None,
                   help="append the request_stats record to this JSONL file")
    s.add_argument("--platform", default=None)
    s.add_argument("--small-n-impl", default="auto",
                   choices=("auto", "vmap", "pallas", "pallas_split"),
                   help="batched implementation for the bucket executables "
                        "(ServeConfig.small_n_impl; docs/SERVING.md)")
    s.add_argument("--scheduler", default="continuous",
                   choices=("continuous", "sync"),
                   help="admission scheduler (ServeConfig.scheduler)")
    s.add_argument("--persist-dir", default=None,
                   help="persistent AOT cache directory (serve/cache.py)")
    s.add_argument("--max-compiles", type=int, default=None,
                   help="fail if more than this many fresh XLA compiles "
                        "happened (0 on a warm --persist-dir = the "
                        "cold-start proof)")
    s.set_defaults(fn=_smoke)
    g = sub.add_parser(
        "loadgen",
        help="closed-loop sync-vs-continuous A/B harness (serve/loadgen.py)",
    )
    g.add_argument("--requests", type=int, default=200)
    g.add_argument("--concurrency", type=int, default=16)
    g.add_argument("--seed", type=int, default=0)
    g.add_argument("--dtype", default="float32")
    g.add_argument("--ledger", default=None,
                   help="append one request_stats record per mode here")
    g.add_argument("--platform", default=None)
    g.add_argument("--small-n-impl", default="auto",
                   choices=("auto", "vmap", "pallas", "pallas_split"))
    g.add_argument("--max-inflight", type=int, default=2,
                   help="continuous mode's unlanded-batch window")
    g.add_argument("--persist-dir", default=None,
                   help="persistent AOT cache directory shared by both modes")
    g.add_argument("--min-speedup", type=float, default=None,
                   help="fail if continuous/sync QPS falls below this "
                        "(leave unset on shared CI hardware)")
    g.set_defaults(fn=_loadgen)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if getattr(args, "platform", None):
        jax.config.update("jax_platforms", args.platform)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
