"""CLI: ``python -m capital_tpu.serve smoke ...`` — the serving self-check.

Runs a small mixed-bucket workload on the local platform (CPU in CI),
writes one serve:request_stats ledger record, and gates on the two
acceptance properties of docs/SERVING.md:

* **zero recompiles**: after warmup over the workload's >= 3 shape
  buckets, every request-driven executable lookup must hit
  (cache misses == 0, hit_rate == 1.0);
* **numerics**: the max per-request residual stays under the pinned
  dtype gate (bench/drivers._tolerance; the lstsq normal-equation
  residual gets the same 10x allowance the qr drivers use — the gram
  squares the conditioning).

With ``--persist-dir`` the smoke exercises the persistent AOT tier, and
``--max-compiles 0`` turns it into the cold-start proof: a SECOND smoke
pointed at the same (now warm) directory must serve the whole workload
with zero fresh XLA compiles — every executable deserializes from disk.
`make serve-smoke` runs exactly that pair, then gates the ledger with
``obs serve-report``.

``python -m capital_tpu.serve loadgen`` is the closed-loop A/B harness
(serve/loadgen.py): the same fixed-seed workload through the sync (PR 4
stop-and-go) and continuous schedulers, one serve:request_stats record
per mode with the queue-wait/device split and the QPS comparison —
`make serve-bench` gates those records via ``obs serve-report``.

``python -m capital_tpu.serve replicas`` is the multi-replica smoke
(serve/router.py): N replicas behind one Router sharing a persistent AOT
cache directory, with an induced kill (in-flight re-dispatch, replacement
warmed from disk) and an induced drain + resume, gated on zero dropped
requests and zero steady-state recompiles — `make serve-replicas` runs
the cold/warm pair and aggregates with ``obs serve-report --aggregate``.
The ``loadgen --replicas N`` variant is the replica-count scaling A/B.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax


def _workload(requests: int, seed: int):
    """Deterministic mixed workload touching >= 3 dense n-buckets, all
    five ops (dense posv/inv/lstsq + the structured posv_blocktri and
    posv_arrowhead), two nrhs buckets, two blocktri (nblocks, b) buckets,
    and two arrowhead border buckets — the mixed dense + structured
    traffic the zero-recompile gate must cover."""
    import numpy as np

    rng = np.random.default_rng(seed)
    ns = (12, 24, 48, 16, 30, 64)  # -> buckets 16 / 32 / 64
    ks = (1, 3)  # -> nrhs buckets 1 / 4
    bts = ((3, 6), (6, 12), (4, 24))  # -> (nblocks, b) buckets
    borders = (3, 6)  # -> arrowhead border buckets 4 / 8
    # 7-long op cycle against the 6-long n cycle (coprime) so blocks sweep
    # the bucket grid; requests arrive in blocks of 4 IDENTICAL shapes
    # (j = i // 4) so the capacity flush path sees full batches, while the
    # pump() cadence below (every 7 submissions, coprime with 4) still
    # catches partial blocks on the deadline path
    ops = ("posv", "inv", "lstsq", "posv_blocktri", "lstsq",
           "posv_arrowhead", "posv")
    out = []
    for i in range(requests):
        j = i // 4
        op = ops[j % len(ops)]
        n = ns[j % len(ns)]
        k = ks[j % len(ks)]
        if op == "lstsq":
            m = 4 * n
            A = rng.standard_normal((m, n))
            B = rng.standard_normal((m, k))
        elif op in ("posv_blocktri", "posv_arrowhead"):
            nb, bb = bts[j % len(bts)]
            G = rng.standard_normal((nb, bb, bb))
            D = G @ G.transpose(0, 2, 1) / bb + 3.0 * np.eye(bb)
            C = 0.3 / np.sqrt(bb) * rng.standard_normal((nb, bb, bb))
            C[0] = 0.0
            A = np.stack([D, C])
            B = rng.standard_normal((nb, bb, k))
            if op == "posv_arrowhead":
                # pack the border/corner/RHS tail (models/arrowhead.pack
                # layout, built host-side in numpy)
                s = borders[j % len(borders)]
                n_t = nb * bb
                F = 0.1 * rng.standard_normal((nb, s, bb))
                S0 = rng.standard_normal((s, s))
                S = S0 @ S0.T / s + 5.0 * np.eye(s)
                Bs = rng.standard_normal((s, k))
                top = np.concatenate(
                    [F.transpose(0, 2, 1).reshape(n_t, s),
                     B.reshape(n_t, k)], axis=1)
                B = np.concatenate(
                    [top, np.concatenate([S, Bs], axis=1)], axis=0)
        else:
            M = rng.standard_normal((n, n))
            A = M @ M.T / n + 3.0 * np.eye(n)
            B = rng.standard_normal((n, k)) if op == "posv" else None
        out.append((op, A, B))
    return out


def _residual(op: str, A, B, x) -> float:
    import numpy as np

    A = np.asarray(A, dtype=np.float64)
    x = np.asarray(x, dtype=np.float64)
    if op == "inv":
        n = A.shape[0]
        return float(np.linalg.norm(A @ x - np.eye(n)) / np.sqrt(n))
    B = np.asarray(B, dtype=np.float64)
    if op in ("posv_blocktri", "posv_arrowhead"):
        # assemble the dense matrix the chain represents and gate the
        # flattened solve residual like dense posv
        _, nb, bb, _ = A.shape
        n = nb * bb
        Ad = np.zeros((n, n))
        for i in range(nb):
            sl = slice(i * bb, (i + 1) * bb)
            Ad[sl, sl] = A[0, i]
            if i:
                up = slice((i - 1) * bb, i * bb)
                Ad[sl, up] = A[1, i]
                Ad[up, sl] = A[1, i].T
        if op == "posv_arrowhead":
            # complete the dense arrowhead from the packed tail: its
            # first s columns are [Bᵀ; S], the rest the flat RHS
            s = B.shape[0] - n
            Af = np.block([[Ad, B[:n, :s]],
                           [B[:n, :s].T, B[n:, :s]]])
            rhs = B[:, s:]
            return float(np.linalg.norm(Af @ x - rhs) / np.linalg.norm(rhs))
        k = B.shape[-1]
        Bf, xf = B.reshape(n, k), x.reshape(n, k)
        return float(np.linalg.norm(Ad @ xf - Bf) / np.linalg.norm(Bf))
    if op == "posv":
        return float(np.linalg.norm(A @ x - B) / np.linalg.norm(B))
    r = A.T @ (A @ x - B)
    return float(np.linalg.norm(r) / np.linalg.norm(A.T @ B))


def _smoke(args) -> int:
    import jax.numpy as jnp

    from capital_tpu.bench.drivers import _tolerance
    from capital_tpu.serve import ServeConfig, SolveEngine

    dtype = jnp.dtype(args.dtype)
    cfg = ServeConfig(
        buckets=(16, 32, 64),
        rows_buckets=(64, 128, 256),
        nrhs_buckets=(1, 4),
        # the structured ladder: the workload's (nblocks, b) chains stay
        # tiny so the interpret-mode scan is cheap, while still touching
        # two rungs of each blocktri axis
        nblocks_buckets=(4, 8),
        block_buckets=(8, 16, 32),
        border_buckets=(4, 8),
        max_batch=4,
        max_delay_s=0.01,
        # every smoke bucket is <= batched_small.SMALL_N_MAX, so 'auto'
        # routes the posv/lstsq buckets through the fused batched-grid
        # kernels (interpret mode on CPU) — the smoke exercises the same
        # dispatch a TPU deployment gets, and latency_ms_small lands in
        # the record for the --max-p99-ms-small serve-report gate.
        small_n_impl=args.small_n_impl,
        scheduler=args.scheduler,
        persist_dir=args.persist_dir,
    )
    eng = SolveEngine(cfg=cfg)
    work = _workload(args.requests, args.seed)
    compiles = eng.warmup(
        (op, A.shape, B.shape if B is not None else None, dtype)
        for op, A, B in work
    )
    print(f"# serve-smoke: warmup compiled {compiles} executables")

    tickets = []
    for i, (op, A, B) in enumerate(work):
        A = jnp.asarray(A, dtype=dtype)
        B = jnp.asarray(B, dtype=dtype) if B is not None else None
        tickets.append(eng.submit(op, A, B))
        if i % 7 == 6:
            # let the oldest queue age past the deadline so the max-delay
            # flush path runs in the smoke, not only the capacity path
            time.sleep(cfg.max_delay_s)
            eng.pump()
    eng.drain()

    failures = []
    tol = _tolerance(dtype)
    worst: dict[str, float] = {}
    buckets_seen = set()
    for (op, A, B), t in zip(work, tickets):
        r = t.result()
        if not r.ok or r.x is None:
            failures.append(f"request {r.request_id} ({op}) failed: {r.error}")
            continue
        if r.bucket is not None:
            buckets_seen.add(r.bucket[:3])  # (op, dtype, a_shape)
        res = _residual(op, A, B, r.x)
        worst[op] = max(worst.get(op, 0.0), res)
        gate = 10 * tol if op == "lstsq" else tol
        if res >= gate:
            failures.append(
                f"request {r.request_id} ({op} {A.shape}) residual "
                f"{res:.3e} >= {gate:.0e}"
            )
    cache = eng.cache_stats()
    n_buckets = len({b[2] for b in buckets_seen})
    rec = eng.emit_stats(
        args.ledger,
        smoke={
            "max_residual": {k: round(v, 12) for k, v in worst.items()},
            "distinct_bucket_shapes": n_buckets,
            "residual_tol": tol,
        },
    )
    print(json.dumps(rec["request_stats"]))
    for op, v in sorted(worst.items()):
        print(f"# serve-smoke: max {op} residual {v:.3e}")
    if n_buckets < 3:
        failures.append(
            f"workload touched only {n_buckets} bucket shapes (< 3)"
        )
    if cache["misses"] or not cache["hits"]:
        failures.append(
            f"steady-state recompile: cache {cache} (expected misses == 0 "
            "after warmup)"
        )
    if args.max_compiles is not None and cache["compiles"] > args.max_compiles:
        disk = cache.get("disk", {})
        failures.append(
            f"cold-start gate: {cache['compiles']} fresh XLA compiles > "
            f"--max-compiles {args.max_compiles} (disk tier: {disk}) — the "
            "persistent cache did not cover the workload"
        )
    if args.trace:
        # per-request span chains (obs/spans.py): ONE serve:trace record,
        # gated in-run at 100% completeness under the pinned bubble
        # tolerance — a request that dropped a stamping site, stamped out
        # of order, or opened an un-spanned gap fails the smoke here, not
        # three tools later
        from capital_tpu.obs import spans

        trec = eng.emit_trace(args.ledger, bubble_tol_ms=args.bubble_tol_ms)
        st = trec["serve_trace"]
        print(
            f"# serve-smoke: traced {st['requests']} requests, "
            f"{st['complete']} complete chains "
            f"(bubble_tol_ms={st['bubble_tol_ms']}, "
            f"dropped={st['dropped']})"
        )
        if st["requests"] != len(tickets):
            failures.append(
                f"trace gate: {st['requests']} traced requests != "
                f"{len(tickets)} submitted — a request slipped through "
                "untraced"
            )
        if st["complete"] != st["requests"] or st["dropped"]:
            for t in st["traces"]:
                for pb in spans.trace_dict_problems(
                        t, st["bubble_tol_ms"]):
                    print(f"#   trace {t['request_id']}: {pb}",
                          file=sys.stderr)
            failures.append(
                f"trace gate: {st['complete']}/{st['requests']} complete "
                f"span chains (dropped={st['dropped']}) — need 100%"
            )
    for f in failures:
        print(f"# serve-smoke FAIL: {f}", file=sys.stderr)
    if failures:
        return 1
    print(
        f"# serve-smoke OK: {len(tickets)} requests, hit_rate "
        f"{cache['hit_rate']:.2f} over {cache['hits']} lookups, "
        f"{n_buckets} bucket shapes, {cache['compiles']} fresh compiles"
    )
    return 0


def _replicas(args) -> int:
    """Multi-replica router smoke (docs/SERVING.md "Multi-replica
    serving"): N replicas behind one Router sharing --persist-dir, the
    loadgen workload submitted through the router with an optional induced
    replica KILL (re-dispatch proof) and an induced DRAIN + resume
    (rolling-restart proof) mid-stream.  Gates: every submitted request
    lands ok under the residual tolerance (zero drops), aggregate
    steady-state cache misses == 0, and with --max-compiles the summed
    fresh-compile count across live replicas (the warm shared-dir run pins
    it at 0 — replicas and the mid-stream replacement all deserialize)."""
    import numpy as np

    from capital_tpu.bench.drivers import _tolerance
    from capital_tpu.serve import loadgen
    from capital_tpu.serve.engine import ServeConfig
    from capital_tpu.serve.replica import make_replica
    from capital_tpu.serve.router import Router, RouterConfig

    cfg = ServeConfig(
        buckets=(16, 32, 64),
        nrhs_buckets=(1, 4),
        max_batch=4,
        max_delay_s=0.002,
        small_n_impl=args.small_n_impl,
        persist_dir=args.persist_dir,
    )
    wl = loadgen.Workload(
        requests=args.requests, concurrency=args.concurrency,
        seed=args.seed, dtype=args.dtype,
    )
    work = loadgen.build_requests(wl)
    specs = loadgen.warmup_specs(wl)
    router = Router(RouterConfig(policy=args.policy))
    for i in range(args.replicas):
        router.add_replica(make_replica(args.replica_mode, f"r{i}", cfg))
    fresh = router.warmup(specs)
    print(f"# serve-replicas: warmup fresh compiles {fresh}")
    router.start()

    failures = []
    tickets = []
    kill_at = len(work) // 2 if args.kill_one else None
    drain_at = (3 * len(work)) // 4 if args.drain_one else None
    drained_id = None
    t_start = time.monotonic()
    for i, (op, A, B) in enumerate(work):
        tickets.append((op, A, B, router.submit(op, A, B)))
        if i == kill_at:
            # abrupt death with a window full of in-flight requests: the
            # pump must observe it and re-dispatch, and the replacement
            # must warm from the SHARED disk tier, not recompile
            router.kill_replica("r0")
            rep = make_replica(args.replica_mode, f"r{args.replicas}", cfg)
            router.add_replica(rep)
            rep_fresh = router.warmup(specs)
            print(f"# serve-replicas: killed r0, replacement "
                  f"r{args.replicas} warmup fresh {rep_fresh}")
            if sum(v or 0 for v in rep_fresh.values()):
                failures.append(
                    f"replacement replica recompiled {rep_fresh} — shared "
                    "persist_dir should have made it a disk hit"
                )
        if i == drain_at:
            live = router.replica_ids(healthy_only=True)
            drained_id = live[-1]
            ok = router.drain_replica(drained_id)
            if not ok:
                failures.append(f"drain_replica({drained_id!r}) timed out")
            per = router.counters()["per_replica"][drained_id]
            if per["outstanding"]:
                failures.append(
                    f"drained replica {drained_id} still has "
                    f"{per['outstanding']} outstanding"
                )
            print(f"# serve-replicas: drained {drained_id} under load "
                  f"(outstanding now {per['outstanding']})")

    tol = _tolerance(np.dtype(args.dtype))
    worst: dict[str, float] = {}
    landed = 0
    for op, A, B, t in tickets:
        r = t.result(timeout=300.0)
        landed += 1
        if not r.ok or r.x is None:
            failures.append(
                f"request {r.request_id} ({op}) failed: {r.error}")
            continue
        res = _residual(op, A, B, r.x)
        worst[op] = max(worst.get(op, 0.0), res)
        gate = 10 * tol if op == "lstsq" else tol
        if res >= gate:
            failures.append(
                f"request {r.request_id} ({op} {A.shape}) residual "
                f"{res:.3e} >= {gate:.0e}"
            )
    wall = time.monotonic() - t_start
    if drained_id is not None:
        router.resume_replica(drained_id)
    counters = router.counters()
    qps = round(landed / wall, 3) if wall > 0 else 0.0
    recs = router.emit_stats(args.ledger, router={
        "qps": qps, "wall_s": round(wall, 6),
        "kill_one": bool(args.kill_one), "drain_one": bool(args.drain_one),
    })
    agg = recs[-1]["request_stats"] if recs else {}
    cache = agg.get("cache", {})
    print(json.dumps(agg))
    for op, v in sorted(worst.items()):
        print(f"# serve-replicas: max {op} residual {v:.3e}")

    if landed != len(work) or counters["completed"] != len(work):
        failures.append(
            f"dropped requests: {landed}/{len(work)} landed, counters "
            f"{counters}"
        )
    if counters["parked"]:
        failures.append(f"{counters['parked']} requests left parked")
    if args.kill_one and not counters["failed_replicas"]:
        failures.append("induced kill not observed (failed_replicas == 0)")
    if cache.get("misses"):
        failures.append(
            f"steady-state recompile: aggregate cache {cache} (expected "
            "misses == 0 after warmup)"
        )
    if (args.max_compiles is not None
            and cache.get("compiles", 0) > args.max_compiles):
        failures.append(
            f"cold-start gate: {cache.get('compiles')} fresh XLA compiles "
            f"across live replicas > --max-compiles {args.max_compiles} "
            f"(disk tier: {cache.get('disk')})"
        )
    router.stop()
    for f in failures:
        print(f"# serve-replicas FAIL: {f}", file=sys.stderr)
    if failures:
        return 1
    print(
        f"# serve-replicas OK: {landed} requests over "
        f"{counters['replicas']} replicas ({args.policy}) in {wall:.3f}s = "
        f"{qps:.1f} qps; redispatched {counters['redispatched']}, "
        f"duplicates {counters['duplicates']}, hit_rate "
        f"{cache.get('hit_rate', 0):.2f}, {cache.get('compiles', 0)} "
        "fresh compiles"
    )
    return 0


def _loadgen_replicas(args) -> int:
    """The replica-count A/B (loadgen.compare_replicas): equal per-client
    offered load against 1 and --replicas replicas sharing --persist-dir;
    the ledger's aggregate record per count carries the `router` block
    with baseline_qps and scaling_efficiency."""
    from capital_tpu.serve import loadgen
    from capital_tpu.serve.engine import ServeConfig

    cfg = ServeConfig(
        buckets=(16, 32, 64),
        nrhs_buckets=(1, 4),
        max_batch=4,
        max_delay_s=0.002,
        small_n_impl=args.small_n_impl,
        max_inflight=args.max_inflight,
        persist_dir=args.persist_dir,
    )
    wl = loadgen.Workload(
        requests=args.requests, concurrency=args.concurrency,
        seed=args.seed, dtype=args.dtype,
    )
    counts = (1, args.replicas) if args.replicas > 1 else (1,)
    results = loadgen.compare_replicas(
        cfg, wl, replica_counts=counts, replica_mode=args.replica_mode,
        client_mode=args.client_mode, policy=args.policy,
        ledger_path=args.ledger,
    )
    failures = []
    for n in counts:
        res = results[n]
        agg = res["records"][-1]["request_stats"]
        cache = agg.get("cache", {})
        print(
            f"# serve-loadgen replicas={n}: {res['requests']} requests, "
            f"{res['clients']} {res['client_mode']} clients in "
            f"{res['wall_s']:.3f}s = {res['qps']:.1f} qps (aggregate "
            f"misses {cache.get('misses')}, compiles {cache.get('compiles')})"
        )
        if res["failed"]:
            failures.append(f"replicas={n}: {res['failed']} requests failed")
        if cache.get("misses"):
            failures.append(
                f"replicas={n}: {cache['misses']} steady-state recompiles"
            )
    eff = results.get("scaling_efficiency")
    if eff is not None:
        print(
            f"# serve-loadgen: {counts[-1]}-replica speedup "
            f"{results['speedup']:.2f}x, scaling efficiency {eff:.2f} "
            f"(1.0 = each replica pulls full single-replica weight)"
        )
        if args.min_scaling is not None and eff < args.min_scaling:
            failures.append(
                f"scaling efficiency {eff:.2f} < --min-scaling "
                f"{args.min_scaling}"
            )
    for f in failures:
        print(f"# serve-loadgen FAIL: {f}", file=sys.stderr)
    if failures:
        return 1
    print("# serve-loadgen OK")
    return 0


def _loadgen(args) -> int:
    if args.replicas:
        if (args.window_s or args.trace or args.min_windows is not None
                or args.deadline_ms is not None):
            print("loadgen: --window-s/--min-windows/--deadline-ms/--trace "
                  "are not supported with --replicas (use the single-"
                  "engine A/B, or `smoke --trace`)", file=sys.stderr)
            return 2
        return _loadgen_replicas(args)

    from capital_tpu.serve import loadgen
    from capital_tpu.serve.engine import ServeConfig

    cfg = ServeConfig(
        buckets=(16, 32, 64),
        rows_buckets=(64, 128, 256),
        nrhs_buckets=(1, 4),
        max_batch=4,
        max_delay_s=0.002,
        small_n_impl=args.small_n_impl,
        max_inflight=args.max_inflight,
        persist_dir=args.persist_dir,
    )
    wl = loadgen.Workload(
        requests=args.requests, concurrency=args.concurrency,
        seed=args.seed, dtype=args.dtype, deadline_ms=args.deadline_ms,
    )
    results = loadgen.compare(cfg, wl, ledger_path=args.ledger,
                              window_s=args.window_s, trace=args.trace)
    failures = []
    nwin = 0
    for mode in ("sync", "continuous"):
        res = results.get(mode)
        if res is None:
            continue
        cache = res["cache"]
        win_note = ""
        if args.window_s:
            nwin += res.get("window_records", 0)
            win_note = f", windows {res.get('window_records', 0)}"
        trace_note = ""
        if args.trace:
            st = res["trace_record"]["serve_trace"]
            trace_note = (f", traces {st['complete']}/{st['requests']} "
                          f"complete")
            if args.deadline_ms is not None:
                trace_note += f", SLO violations {st['violations']}"
        print(
            f"# serve-loadgen {mode}: {res['requests']} requests in "
            f"{res['wall_s']:.3f}s = {res['qps']:.1f} qps "
            f"(concurrency {wl.concurrency}, cache misses "
            f"{cache['misses']}, compiles {cache['compiles']}"
            + win_note + trace_note + ")"
        )
        if res["failed"]:
            failures.append(f"{mode}: {res['failed']} requests failed")
        if cache["misses"]:
            failures.append(
                f"{mode}: {cache['misses']} steady-state recompiles "
                "(warmup must cover the workload grid)"
            )
    if args.min_windows is not None:
        # loud-when-dead: asking for a window floor without enabling the
        # telemetry that produces windows is a wiring bug, not a pass
        if not args.window_s:
            failures.append(
                "--min-windows requires --window-s (telemetry disabled, "
                "no windows can ever close)"
            )
        elif nwin < args.min_windows:
            failures.append(
                f"{nwin} serve:window record(s) across modes < "
                f"--min-windows {args.min_windows} (run longer, or "
                "shrink --window-s)"
            )
    if results.get("speedup") is not None:
        print(f"# serve-loadgen: continuous/sync speedup "
              f"{results['speedup']:.2f}x")
        if args.min_speedup is not None and results["speedup"] < args.min_speedup:
            failures.append(
                f"speedup {results['speedup']:.2f}x < --min-speedup "
                f"{args.min_speedup}"
            )
    for f in failures:
        print(f"# serve-loadgen FAIL: {f}", file=sys.stderr)
    if failures:
        return 1
    print("# serve-loadgen OK")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="capital_tpu.serve")
    sub = p.add_subparsers(dest="cmd", required=True)
    s = sub.add_parser("smoke", help="mixed-bucket serving self-check")
    s.add_argument("--requests", type=int, default=50)
    s.add_argument("--dtype", default="float32")
    s.add_argument("--seed", type=int, default=0)
    s.add_argument("--ledger", default=None,
                   help="append the request_stats record to this JSONL file")
    s.add_argument("--platform", default=None)
    s.add_argument("--small-n-impl", default="auto",
                   choices=("auto", "vmap", "pallas", "pallas_split"),
                   help="batched implementation for the bucket executables "
                        "(ServeConfig.small_n_impl; docs/SERVING.md)")
    s.add_argument("--scheduler", default="continuous",
                   choices=("continuous", "sync"),
                   help="admission scheduler (ServeConfig.scheduler)")
    s.add_argument("--persist-dir", default=None,
                   help="persistent AOT cache directory (serve/cache.py)")
    s.add_argument("--max-compiles", type=int, default=None,
                   help="fail if more than this many fresh XLA compiles "
                        "happened (0 on a warm --persist-dir = the "
                        "cold-start proof)")
    s.add_argument("--trace", action="store_true",
                   help="emit the per-request span-chain record "
                        "(serve:trace, obs/spans.py) and gate the run on "
                        "100%% complete monotonic chains")
    s.add_argument("--bubble-tol-ms", type=float, default=25.0,
                   help="largest un-spanned host-side gap a chain may "
                        "carry and still count complete "
                        "(spans.DEFAULT_BUBBLE_TOL_MS)")
    s.set_defaults(fn=_smoke)
    g = sub.add_parser(
        "loadgen",
        help="closed-loop sync-vs-continuous A/B harness (serve/loadgen.py)",
    )
    g.add_argument("--requests", type=int, default=200)
    g.add_argument("--concurrency", type=int, default=16)
    g.add_argument("--seed", type=int, default=0)
    g.add_argument("--dtype", default="float32")
    g.add_argument("--ledger", default=None,
                   help="append one request_stats record per mode here")
    g.add_argument("--platform", default=None)
    g.add_argument("--small-n-impl", default="auto",
                   choices=("auto", "vmap", "pallas", "pallas_split"))
    g.add_argument("--max-inflight", type=int, default=2,
                   help="continuous mode's unlanded-batch window")
    g.add_argument("--persist-dir", default=None,
                   help="persistent AOT cache directory shared by both modes")
    g.add_argument("--min-speedup", type=float, default=None,
                   help="fail if continuous/sync QPS falls below this "
                        "(leave unset on shared CI hardware)")
    g.add_argument("--window-s", type=float, default=None,
                   help="enable rolling-window telemetry "
                        "(serve/telemetry.py) with this window length; "
                        "appends one serve:window record per closed "
                        "non-empty window")
    g.add_argument("--min-windows", type=int, default=None,
                   help="fail unless at least this many serve:window "
                        "records were emitted across both modes "
                        "(requires --window-s)")
    g.add_argument("--deadline-ms", type=float, default=None,
                   help="per-request SLO deadline: traces carry "
                        "slack-at-dispatch and violation attribution "
                        "(most useful with --trace)")
    g.add_argument("--trace", action="store_true",
                   help="emit one serve:trace span-chain record per mode "
                        "(not supported with --replicas)")
    g.add_argument("--replicas", type=int, default=0,
                   help="run the replica-count A/B instead: 1 vs N "
                        "replicas behind a router at equal per-client "
                        "offered load (loadgen.compare_replicas)")
    g.add_argument("--replica-mode", default="thread",
                   choices=("thread", "process"),
                   help="replica transport: in-process threads (CI) or "
                        "spawned engine processes")
    g.add_argument("--client-mode", default="thread",
                   choices=("thread", "process"),
                   help="closed-loop client transport for the router A/B")
    g.add_argument("--policy", default="least_loaded",
                   help="router dispatch policy (least_loaded or "
                        "bucket_affinity)")
    g.add_argument("--min-scaling", type=float, default=None,
                   help="fail if N-replica scaling efficiency falls below "
                        "this (leave unset on shared CI hardware — this "
                        "rig may have fewer cores than replicas)")
    g.set_defaults(fn=_loadgen)
    r = sub.add_parser(
        "replicas",
        help="multi-replica router smoke: shared persistent cache, "
             "induced kill + drain, zero-drop and recompile gates",
    )
    r.add_argument("--replicas", type=int, default=2)
    r.add_argument("--requests", type=int, default=48)
    r.add_argument("--concurrency", type=int, default=8,
                   help="recorded in the workload (submission here is "
                        "paced by the router, not a client pool)")
    r.add_argument("--seed", type=int, default=0)
    r.add_argument("--dtype", default="float32")
    r.add_argument("--ledger", default=None,
                   help="append per-replica + aggregate request_stats "
                        "records here")
    r.add_argument("--platform", default=None)
    r.add_argument("--small-n-impl", default="pallas",
                   choices=("auto", "vmap", "pallas", "pallas_split"),
                   help="pallas (interpret on CPU) keeps every executable "
                        "pure-HLO and therefore disk-persistable — the "
                        "shared-cache story this smoke proves")
    r.add_argument("--replica-mode", default="thread",
                   choices=("thread", "process"))
    r.add_argument("--policy", default="bucket_affinity",
                   help="router dispatch policy; bucket_affinity is the "
                        "cache-locality default here so the kill also "
                        "proves the rebalance-is-a-disk-hit property")
    r.add_argument("--persist-dir", default=None,
                   help="shared persistent AOT cache directory")
    r.add_argument("--kill-one", action="store_true",
                   help="kill replica r0 mid-stream and register a "
                        "replacement (re-dispatch + disk-warm proof)")
    r.add_argument("--drain-one", action="store_true",
                   help="drain one replica under load, then resume it "
                        "(rolling-restart proof)")
    r.add_argument("--max-compiles", type=int, default=None,
                   help="fail if live replicas' summed fresh XLA compiles "
                        "exceed this (0 on a warm shared --persist-dir)")
    r.set_defaults(fn=_replicas)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if getattr(args, "platform", None):
        jax.config.update("jax_platforms", args.platform)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
