"""Serving telemetry: latencies, queue depth, occupancy, cache hit-rate.

One `Collector` per SolveEngine accumulates per-request and per-batch facts
host-side (pure Python — nothing here touches a device) and snapshots them
into a `request_stats` block: the schema_version-tagged record payload
`obs.ledger` validates (ledger.validate_request_stats), `obs serve-report`
summarizes, and `ledger.diff` exempts from the metric-regression check the
same way event/robust records are exempt (a served mix's latency profile is
workload, not a kernel regression).

Latency percentiles come from bench/harness.percentiles — the same
nearest-rank p50/p95/p99 the bench report lines carry, so a request_stats
record and a bench row read on one scale.
"""

from __future__ import annotations

import random
from collections import Counter

from capital_tpu.bench.harness import percentiles

#: Default bound on each raw-sample population a Collector retains.  A
#: long-running replica records forever; without a cap its four latency
#: lists grow without limit.  High enough that every tier-1 smoke and
#: loadgen run stays exact (capped == False).
DEFAULT_SAMPLE_CAP = 8192


class Reservoir:
    """Bounded sample population: the first `cap` values verbatim, then
    uniform reservoir replacement (algorithm R) with a deterministic
    per-instance seed — two replicas under identical traffic snapshot
    identical populations.  Iterable/len-able so `percentiles(reservoir)`
    and `list(reservoir)` read like the list it replaces; `count` is the
    TRUE number of values ever recorded and `capped` says whether the
    population is a subsample (the signal merge_snapshots degrades on)."""

    __slots__ = ("cap", "count", "_items", "_rng")

    def __init__(self, cap: int = DEFAULT_SAMPLE_CAP):
        if cap < 1:
            raise ValueError(f"reservoir cap must be >= 1, got {cap}")
        self.cap = cap
        self.count = 0
        self._items: list[float] = []
        self._rng = random.Random(0x5EED)

    def append(self, v: float) -> None:
        self.count += 1
        if len(self._items) < self.cap:
            self._items.append(v)
            return
        j = self._rng.randrange(self.count)
        if j < self.cap:
            self._items[j] = v

    @property
    def capped(self) -> bool:
        return self.count > self.cap

    def __len__(self) -> int:
        return len(self._items)

    def __iter__(self):
        return iter(self._items)

    def __bool__(self) -> bool:
        return bool(self._items)


class Collector:
    """Accumulates serving telemetry; snapshot() emits the request_stats
    block documented in docs/SERVING.md."""

    def __init__(self, replica_id: str | None = None,
                 sample_cap: int = DEFAULT_SAMPLE_CAP):
        # multi-replica deployments tag each collector with its replica's
        # id so the router / `obs serve-report --aggregate` can tell the
        # per-replica records apart (docs/SERVING.md "Multi-replica
        # serving"); None (the single-engine default) keeps the snapshot
        # schema exactly what it always was.
        self.replica_id = replica_id
        self.requests = 0
        self.ok = 0
        self.flagged = 0  # robust-flagged (breakdown detected, result kept)
        self.failed = 0  # no result at all (ingest fault / rejected)
        self.ops: Counter = Counter()
        # every raw-sample population is reservoir-capped (Reservoir) so a
        # long-running replica's memory stays bounded; counts stay exact,
        # percentiles degrade to a uniform subsample past the cap and the
        # snapshot says so (samples_capped).
        self.latencies_s = Reservoir(sample_cap)
        self.queue_depth_max = 0
        self.batches = 0
        self.occupancies: list[float] = []
        # requests served by the batched-grid small-N kernels — tracked as
        # their own latency population so `obs serve-report` can gate
        # small-bucket p99 (--max-p99-ms-small) separately from the large
        # buckets, whose solve time dominates any mixed percentile.
        self.latencies_small_s = Reservoir(sample_cap)
        # the two halves of each dispatched request's latency (executor
        # timing contract): queue-wait is scheduling policy, device is
        # compute + transfer.  Separate populations (not per-request pairs)
        # because the report gates each tail independently
        # (--max-queue-wait-ms); requests that never dispatched (ingest
        # faults, rejects) contribute to neither.
        self.queue_waits_s = Reservoir(sample_cap)
        self.devices_s = Reservoir(sample_cap)
        # optional live-telemetry tap (serve/telemetry.WindowAggregator,
        # attached by SolveEngine.enable_telemetry): every record/note
        # forwards, so the rolling windows see exactly what the snapshot
        # sees.  None (the default) adds one attribute check per note.
        self.window = None
        # posv_blocktri algorithm split ('scan' vs 'partitioned' — which
        # chain driver the request's compiled program runs, resolved by
        # the engine at submit time from static geometry).  Optional
        # block, like latency_ms_small: absent until blocktri traffic
        # happens.
        self.blocktri_impls: Counter = Counter()
        # accuracy_tier='guaranteed' refinement telemetry (the engine's
        # _refine_sink feeds it per landed request).  Sweep counts are
        # data-dependent — tracing prices exactly one sweep, so the
        # MEASURED population here is the only place the true refinement
        # cost is visible.  Optional block, like latency_ms_small: absent
        # until guaranteed-tier traffic happens.
        self.refine_iters: list[int] = []
        self.refine_resids: list[float] = []
        self.refine_converged = 0
        self.refine_nonconverged = 0

    # ---- feeding -----------------------------------------------------------

    def note_blocktri_impl(self, algorithm: str) -> None:
        self.blocktri_impls[algorithm] += 1

    def note_refine(self, iters: int, converged: bool,
                    resid: float) -> None:
        self.refine_iters.append(int(iters))
        self.refine_resids.append(float(resid))
        if converged:
            self.refine_converged += 1
        else:
            self.refine_nonconverged += 1

    def note_queue_depth(self, depth: int) -> None:
        self.queue_depth_max = max(self.queue_depth_max, depth)
        if self.window is not None:
            self.window.note_queue_depth(depth)

    def note_batch(self, occupancy: float, bucket=None) -> None:
        self.batches += 1
        self.occupancies.append(occupancy)
        if self.window is not None:
            self.window.note_batch(occupancy, bucket=bucket)

    def record_request(
        self, op: str, latency_s: float, ok: bool,
        flagged: bool = False, failed: bool = False, small: bool = False,
        queue_wait_s: float | None = None, device_s: float | None = None,
        bucket=None,
    ) -> None:
        self.requests += 1
        self.ops[op] += 1
        self.latencies_s.append(latency_s)
        if small:
            self.latencies_small_s.append(latency_s)
        if queue_wait_s is not None:
            self.queue_waits_s.append(queue_wait_s)
        if device_s is not None:
            self.devices_s.append(device_s)
        if failed:
            self.failed += 1
        elif flagged:
            self.flagged += 1
        elif ok:
            self.ok += 1
        if self.window is not None:
            self.window.note_request(op, latency_s, ok=ok, failed=failed,
                                     bucket=bucket)

    # ---- reporting ---------------------------------------------------------

    def snapshot(self, cache: dict | None = None, *,
                 factor_cache: dict | None = None,
                 samples: bool = False) -> dict:
        """The request_stats block.  `cache` is the engine's cache_stats()
        (hits/misses/hit_rate/warmup_compiles); zeros when absent so the
        schema stays total.  `factor_cache` is the FactorCache counter
        block (serve/factorcache.py stats()) — attached ONLY when factor
        traffic happened (lookups or installs), the same optional-block
        discipline as latency_ms_small, so pre-PR-12 records and engines
        that never serve factor ops keep their exact schema and `obs
        serve-report --min-residency-hit-rate` can fail loudly when the
        block is absent rather than passing on a vacuous 1.0.
        `samples=True` attaches the raw latency populations (seconds) so
        merge_snapshots can pool percentiles exactly instead of
        max-of-p99 — meant for router-internal aggregation, not for
        ledger records (strip it before append)."""
        from capital_tpu.obs.ledger import SCHEMA_VERSION

        lat = (
            {k: round(v * 1e3, 4)
             for k, v in percentiles(self.latencies_s).items()}
            if self.latencies_s
            else {"p50": 0.0, "p95": 0.0, "p99": 0.0}
        )
        occ = self.occupancies
        snap = {
            "schema_version": SCHEMA_VERSION,
            "requests": self.requests,
            "ok": self.ok,
            "flagged": self.flagged,
            "failed": self.failed,
            "ops": dict(self.ops),
            "latency_ms": lat,
            "queue_depth_max": self.queue_depth_max,
            "batches": self.batches,
            "batch_occupancy_mean": (
                round(sum(occ) / len(occ), 4) if occ else 0.0
            ),
            "cache": dict(cache) if cache else {
                "hits": 0, "misses": 0, "warmup_compiles": 0,
                "hit_rate": 1.0,
            },
        }
        # small-N split: present only when small-bucket traffic happened,
        # so pre-existing records (and engines that never route pallas)
        # keep the exact schema they always had.
        if self.latencies_small_s:
            snap["requests_small"] = self.latencies_small_s.count
            snap["latency_ms_small"] = {
                k: round(v * 1e3, 4)
                for k, v in percentiles(self.latencies_small_s).items()
            }
        # queue-wait / on-device split: present only when dispatched traffic
        # happened (same optional-block discipline as latency_ms_small, so
        # records from older engines stay valid and the report's
        # --max-queue-wait-ms gate can fail loudly when the split is absent
        # rather than silently passing on zeros).
        if self.queue_waits_s:
            snap["queue_wait_ms"] = {
                k: round(v * 1e3, 4)
                for k, v in percentiles(self.queue_waits_s).items()
            }
        if self.devices_s:
            snap["device_ms"] = {
                k: round(v * 1e3, 4)
                for k, v in percentiles(self.devices_s).items()
            }
        # posv_blocktri scan/partitioned split: same optional-block
        # discipline — absent without blocktri traffic, so older records
        # keep their schema and `obs serve-report` prints it only where
        # it means something.
        if self.blocktri_impls:
            snap["blocktri_impls"] = dict(self.blocktri_impls)
        # guaranteed-tier refinement block: measured sweep counts and the
        # worst landed backward error.  Iteration percentiles are COUNTS
        # (not ms — no 1e3 scaling); resid_max is the honest aggregate of
        # a quantity whose mean is meaningless across conditioning mixes.
        if self.refine_iters:
            n_ref = len(self.refine_iters)
            snap["refine"] = {
                "requests": n_ref,
                "converged": self.refine_converged,
                "nonconverged": self.refine_nonconverged,
                "converged_frac": round(self.refine_converged / n_ref, 4),
                "iters": {
                    k: round(v, 4)
                    for k, v in percentiles(
                        [float(i) for i in self.refine_iters]).items()
                },
                "iters_max": max(self.refine_iters),
                # NaN residuals (factor breakdown under the fast dtype)
                # already count as nonconverged; keep them out of the max
                # so it stays an orderable worst case (r == r is the
                # NaN filter)
                "resid_max": max(
                    (r for r in self.refine_resids if r == r), default=0.0
                ),
            }
        if factor_cache and (factor_cache.get("hits", 0)
                             + factor_cache.get("misses", 0)
                             + factor_cache.get("installs", 0)) > 0:
            snap["factor_cache"] = dict(factor_cache)
        if self.replica_id is not None:
            snap["replica_id"] = str(self.replica_id)
        # reservoir honesty marker: set the moment ANY raw population
        # outgrew its cap.  merge_snapshots reads it to refuse pooling a
        # subsample as if it were the full population (worst-tail max is
        # the honest degraded answer); absent on uncapped runs so the
        # schema stays what it always was.
        if any(r.capped for r in (self.latencies_s, self.latencies_small_s,
                                  self.queue_waits_s, self.devices_s)):
            snap["samples_capped"] = True
        if samples:
            snap["samples"] = {
                "latency_s": list(self.latencies_s),
                "latency_small_s": list(self.latencies_small_s),
                "queue_wait_s": list(self.queue_waits_s),
                "device_s": list(self.devices_s),
            }
        return snap

    def emit(self, path: str | None, *, grid=None, config=None,
             cache: dict | None = None, factor_cache: dict | None = None,
             **extra) -> dict:
        """Assemble (and append, when `path` is given) ONE ledger record
        carrying the snapshot — kind 'serve:request_stats', same manifest
        discipline as every other ledger row."""
        from capital_tpu.obs import ledger

        rec = ledger.record(
            "serve:request_stats",
            ledger.manifest(grid=grid, config=config),
            request_stats=self.snapshot(cache, factor_cache=factor_cache),
            **extra,
        )
        if path:
            ledger.append(path, rec)
        return rec


# ---- cross-replica aggregation (pure; docs/SERVING.md) --------------------

#: percentile block -> the samples-block population it pools from.
_SAMPLE_KEYS = {
    "latency_ms": "latency_s",
    "latency_ms_small": "latency_small_s",
    "queue_wait_ms": "queue_wait_s",
    "device_ms": "device_s",
}


def _merge_pcts(snaps: list[dict], name: str) -> dict | None:
    """One merged percentile block across `snaps`.  Pools the raw sample
    populations when EVERY contributing snapshot carries them IN FULL
    (exact percentiles of the union); otherwise the elementwise max — the
    honest degraded answer, because a worst-tail bound is the only
    percentile that survives aggregation without the populations.  A
    reservoir-capped contributor (`samples_capped`) degrades the merge the
    same way: its samples are a uniform subsample, and pooling a subsample
    as if it were the population would silently bias the union's tail."""
    present = [s for s in snaps if name in s]
    if name == "latency_ms":
        present = snaps  # total block: every snapshot has it
    if not present:
        return None
    skey = _SAMPLE_KEYS[name]
    if all("samples" in s and not s.get("samples_capped")
           for s in present):
        pool = [v for s in present for v in s["samples"].get(skey, ())]
        if not pool:
            return {"p50": 0.0, "p95": 0.0, "p99": 0.0}
        return {k: round(v * 1e3, 4) for k, v in percentiles(pool).items()}
    out = {"p50": 0.0, "p95": 0.0, "p99": 0.0}
    for s in present:
        blk = s.get(name) or {}
        for p in out:
            out[p] = max(out[p], float(blk.get(p, 0.0)))
    return out


def merge_snapshots(snaps: list[dict]) -> dict:
    """Fold N replica-tagged request_stats snapshots into ONE aggregate
    block (pure — unit-testable without a ledger or an engine):

    * counts (requests/ok/flagged/failed/batches, per-op) sum; queue depth
      takes the max (depths are per-replica queues, not one shared queue);
    * occupancy is the batch-weighted mean — N half-full replicas must not
      average into a healthy-looking number just because one was idle;
    * percentiles pool from the raw sample populations when present
      (Collector.snapshot(samples=True)), else take the worst tail
      (elementwise max) — never a mean of percentiles, which is a number
      with no definition;
    * cache counters sum (incl. the disk tier when any replica persists)
      with hit_rate recomputed from the summed lookups;
    * the result carries ``replicas`` (how many snapshots merged) and
      ``replica_ids``, drops per-replica tags/samples, and stays valid
      under obs.ledger.validate_request_stats.
    """
    if not snaps:
        raise ValueError("merge_snapshots needs at least one snapshot")
    ops: Counter = Counter()
    bt_impls: Counter = Counter()
    for s in snaps:
        ops.update(s.get("ops") or {})
        bt_impls.update(s.get("blocktri_impls") or {})
    batches = sum(int(s.get("batches", 0)) for s in snaps)
    occ_w = sum(float(s.get("batch_occupancy_mean", 0.0))
                * int(s.get("batches", 0)) for s in snaps)
    merged = {
        "schema_version": snaps[0].get("schema_version"),
        "requests": sum(int(s.get("requests", 0)) for s in snaps),
        "ok": sum(int(s.get("ok", 0)) for s in snaps),
        "flagged": sum(int(s.get("flagged", 0)) for s in snaps),
        "failed": sum(int(s.get("failed", 0)) for s in snaps),
        "ops": dict(ops),
        "latency_ms": _merge_pcts(snaps, "latency_ms"),
        "queue_depth_max": max(int(s.get("queue_depth_max", 0))
                               for s in snaps),
        "batches": batches,
        "batch_occupancy_mean": (
            round(occ_w / batches, 4) if batches else 0.0
        ),
        "replicas": len(snaps),
    }
    if bt_impls:
        merged["blocktri_impls"] = dict(bt_impls)
    ids = [s["replica_id"] for s in snaps if s.get("replica_id")]
    if ids:
        merged["replica_ids"] = sorted(ids)
    cache = {"hits": 0, "misses": 0, "warmup_compiles": 0, "compiles": 0,
             "entries": 0}
    disk: dict | None = None
    for s in snaps:
        c = s.get("cache") or {}
        for k in cache:
            cache[k] += int(c.get(k, 0))
        d = c.get("disk")
        if d:
            disk = disk or {}
            for k, v in d.items():
                disk[k] = disk.get(k, 0) + int(v)
    lookups = cache["hits"] + cache["misses"]
    cache["hit_rate"] = (cache["hits"] / lookups) if lookups else 1.0
    if disk is not None:
        cache["disk"] = disk
    merged["cache"] = cache
    # factor-residency counters sum like the cache block (hit_rate
    # recomputed from summed lookups, never averaged); present only when
    # some replica saw factor traffic — same optional-block discipline
    # the snapshot itself follows.
    fsnaps = [s["factor_cache"] for s in snaps if s.get("factor_cache")]
    if fsnaps:
        fc = {k: 0 for k in ("hits", "misses", "evictions", "installs",
                             "released", "downdate_degrades", "entries",
                             "bytes", "budget_bytes")}
        for f in fsnaps:
            for k in fc:
                fc[k] += int(f.get(k, 0))
        flook = fc["hits"] + fc["misses"]
        fc["hit_rate"] = (fc["hits"] / flook) if flook else 1.0
        merged["factor_cache"] = fc
    for name in ("latency_ms_small", "queue_wait_ms", "device_ms"):
        blk = _merge_pcts(snaps, name)
        if blk is not None:
            merged[name] = blk
    if any("requests_small" in s for s in snaps):
        merged["requests_small"] = sum(int(s.get("requests_small", 0))
                                       for s in snaps)
    # guaranteed-tier refinement: counts sum with converged_frac recomputed
    # (never averaged); iteration percentiles take the elementwise max
    # (they are counts, not samples — no population to pool) and resid_max
    # the max, both honest worst-case bounds across replicas.
    rsnaps = [s["refine"] for s in snaps if s.get("refine")]
    if rsnaps:
        n_ref = sum(int(r.get("requests", 0)) for r in rsnaps)
        conv = sum(int(r.get("converged", 0)) for r in rsnaps)
        iters = {"p50": 0.0, "p95": 0.0, "p99": 0.0}
        for r in rsnaps:
            for p in iters:
                iters[p] = max(iters[p],
                               float((r.get("iters") or {}).get(p, 0.0)))
        merged["refine"] = {
            "requests": n_ref,
            "converged": conv,
            "nonconverged": sum(int(r.get("nonconverged", 0))
                                for r in rsnaps),
            "converged_frac": round(conv / n_ref, 4) if n_ref else 1.0,
            "iters": iters,
            "iters_max": max(int(r.get("iters_max", 0)) for r in rsnaps),
            "resid_max": max(float(r.get("resid_max", 0.0))
                             for r in rsnaps),
        }
    return merged
