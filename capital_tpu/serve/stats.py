"""Serving telemetry: latencies, queue depth, occupancy, cache hit-rate.

One `Collector` per SolveEngine accumulates per-request and per-batch facts
host-side (pure Python — nothing here touches a device) and snapshots them
into a `request_stats` block: the schema_version-tagged record payload
`obs.ledger` validates (ledger.validate_request_stats), `obs serve-report`
summarizes, and `ledger.diff` exempts from the metric-regression check the
same way event/robust records are exempt (a served mix's latency profile is
workload, not a kernel regression).

Latency percentiles come from bench/harness.percentiles — the same
nearest-rank p50/p95/p99 the bench report lines carry, so a request_stats
record and a bench row read on one scale.
"""

from __future__ import annotations

from collections import Counter

from capital_tpu.bench.harness import percentiles


class Collector:
    """Accumulates serving telemetry; snapshot() emits the request_stats
    block documented in docs/SERVING.md."""

    def __init__(self):
        self.requests = 0
        self.ok = 0
        self.flagged = 0  # robust-flagged (breakdown detected, result kept)
        self.failed = 0  # no result at all (ingest fault / rejected)
        self.ops: Counter = Counter()
        self.latencies_s: list[float] = []
        self.queue_depth_max = 0
        self.batches = 0
        self.occupancies: list[float] = []
        # requests served by the batched-grid small-N kernels — tracked as
        # their own latency population so `obs serve-report` can gate
        # small-bucket p99 (--max-p99-ms-small) separately from the large
        # buckets, whose solve time dominates any mixed percentile.
        self.latencies_small_s: list[float] = []
        # the two halves of each dispatched request's latency (executor
        # timing contract): queue-wait is scheduling policy, device is
        # compute + transfer.  Separate populations (not per-request pairs)
        # because the report gates each tail independently
        # (--max-queue-wait-ms); requests that never dispatched (ingest
        # faults, rejects) contribute to neither.
        self.queue_waits_s: list[float] = []
        self.devices_s: list[float] = []

    # ---- feeding -----------------------------------------------------------

    def note_queue_depth(self, depth: int) -> None:
        self.queue_depth_max = max(self.queue_depth_max, depth)

    def note_batch(self, occupancy: float) -> None:
        self.batches += 1
        self.occupancies.append(occupancy)

    def record_request(
        self, op: str, latency_s: float, ok: bool,
        flagged: bool = False, failed: bool = False, small: bool = False,
        queue_wait_s: float | None = None, device_s: float | None = None,
    ) -> None:
        self.requests += 1
        self.ops[op] += 1
        self.latencies_s.append(latency_s)
        if small:
            self.latencies_small_s.append(latency_s)
        if queue_wait_s is not None:
            self.queue_waits_s.append(queue_wait_s)
        if device_s is not None:
            self.devices_s.append(device_s)
        if failed:
            self.failed += 1
        elif flagged:
            self.flagged += 1
        elif ok:
            self.ok += 1

    # ---- reporting ---------------------------------------------------------

    def snapshot(self, cache: dict | None = None) -> dict:
        """The request_stats block.  `cache` is the engine's cache_stats()
        (hits/misses/hit_rate/warmup_compiles); zeros when absent so the
        schema stays total."""
        from capital_tpu.obs.ledger import SCHEMA_VERSION

        lat = (
            {k: round(v * 1e3, 4)
             for k, v in percentiles(self.latencies_s).items()}
            if self.latencies_s
            else {"p50": 0.0, "p95": 0.0, "p99": 0.0}
        )
        occ = self.occupancies
        snap = {
            "schema_version": SCHEMA_VERSION,
            "requests": self.requests,
            "ok": self.ok,
            "flagged": self.flagged,
            "failed": self.failed,
            "ops": dict(self.ops),
            "latency_ms": lat,
            "queue_depth_max": self.queue_depth_max,
            "batches": self.batches,
            "batch_occupancy_mean": (
                round(sum(occ) / len(occ), 4) if occ else 0.0
            ),
            "cache": dict(cache) if cache else {
                "hits": 0, "misses": 0, "warmup_compiles": 0,
                "hit_rate": 1.0,
            },
        }
        # small-N split: present only when small-bucket traffic happened,
        # so pre-existing records (and engines that never route pallas)
        # keep the exact schema they always had.
        if self.latencies_small_s:
            snap["requests_small"] = len(self.latencies_small_s)
            snap["latency_ms_small"] = {
                k: round(v * 1e3, 4)
                for k, v in percentiles(self.latencies_small_s).items()
            }
        # queue-wait / on-device split: present only when dispatched traffic
        # happened (same optional-block discipline as latency_ms_small, so
        # records from older engines stay valid and the report's
        # --max-queue-wait-ms gate can fail loudly when the split is absent
        # rather than silently passing on zeros).
        if self.queue_waits_s:
            snap["queue_wait_ms"] = {
                k: round(v * 1e3, 4)
                for k, v in percentiles(self.queue_waits_s).items()
            }
        if self.devices_s:
            snap["device_ms"] = {
                k: round(v * 1e3, 4)
                for k, v in percentiles(self.devices_s).items()
            }
        return snap

    def emit(self, path: str | None, *, grid=None, config=None,
             cache: dict | None = None, **extra) -> dict:
        """Assemble (and append, when `path` is given) ONE ledger record
        carrying the snapshot — kind 'serve:request_stats', same manifest
        discipline as every other ledger row."""
        from capital_tpu.obs import ledger

        rec = ledger.record(
            "serve:request_stats",
            ledger.manifest(grid=grid, config=config),
            request_stats=self.snapshot(cache),
            **extra,
        )
        if path:
            ledger.append(path, rec)
        return rec
