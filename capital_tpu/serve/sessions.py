"""Streaming state-space sessions: the client half of the session protocol.

A *session* is a long-lived solve context whose blocktri chain factor
stays resident in the engine's FactorCache (token = session id) while the
client streams blocks through a sliding window (docs/SERVING.md
"Streaming sessions"):

* ``open``     — seed the resident chain from the initial window blocks
  (engine op ``session_open``; one O(nblocks·b³) factorization).
* ``append``   — extend the resident factor by the NEW blocks only
  (``session_append`` riding ``models/blocktri.extend`` from the stored
  carry): O(new-blocks), never O(window).
* ``solve``    — forward/backward sweeps against the resident factor
  (``session_solve``), honoring the per-request ``accuracy_tier``
  ('guaranteed' refines against the session's own resident factor).
* ``downdate`` / ``contract`` — drop the k OLDEST blocks
  (``session_contract`` riding ``models/blocktri.contract``): a pure
  slice of the resident factor, bitwise-equal to refactoring the
  truncated chain.  ``append`` + ``contract`` give O(new-blocks) sliding
  windows.
* ``close``    — release the resident factor.

The SessionManager mirrors the resident chain with a host-side window
matrix (D, C as NumPy arrays) so every ``solve`` can ship the CURRENT
window operand the guaranteed tier computes residuals against.  The one
subtle piece of bookkeeping lives at ``contract`` time: the contracted
factor represents the MARGINAL precision of the surviving window — its
head diagonal is L_k·L_kᵀ and its head coupling is zero (see the
``models/blocktri.contract`` docstring) — so the manager rebuilds its
window head from the new head factor block the engine returns:
``D[0] ← L_k·L_kᵀ``, ``C[0] ← 0``.  Skipping that update would desync
the window from the factor and fail the engine's out-of-sync check on
the next solve.

Loudness contract: when the resident factor was EVICTED under cache
pressure, the engine fails the request with a tombstone-loud
``SessionEvicted:`` error; the manager converts it into the typed
:class:`SessionEvicted` exception (dropping its local mirror — the
state is gone) so clients re-seed explicitly via :meth:`open`, the one
sanctioned path back (it clears the tombstone).  Re-opening a known
session id counts as a ``reseed`` in the session stats.

Counters accumulate here and surface through
:meth:`SessionManager.emit_session_stats` as ONE ``serve:session_stats``
ledger record (obs.ledger.validate_session_stats validates it; ``obs
serve-report --min-session-hit-rate / --max-reseeds`` gate it).  The
session hit-rate is the residency story's whole justification: a miss
means a full O(window) re-seed where a hit was O(new-blocks).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from capital_tpu.serve.executor import Response

#: ever-incremented schema tag for the session_stats block.
SESSION_STATS_SCHEMA = 1


class SessionEvicted(RuntimeError):
    """The session's resident factor was evicted under cache pressure.

    Raised (never swallowed) by SessionManager methods when the engine
    answers with a tombstone-loud ``SessionEvicted:`` failure.  The local
    window mirror is dropped before raising — the only way forward is
    :meth:`SessionManager.open` with a fresh window (counted as a
    reseed)."""

    def __init__(self, sid: str, error: str):
        super().__init__(error)
        self.sid = sid


@dataclasses.dataclass
class _SessionState:
    """Host-side mirror of one resident session chain."""

    b: int
    dtype: np.dtype
    D: np.ndarray        # (nblocks, b, b) current window diagonal blocks
    C: np.ndarray        # (nblocks, b, b) current window couplings; C[0] == 0
    dropped: int = 0     # blocks contracted away since open (whole-chain)
    appends: int = 0
    solves: int = 0
    contracts: int = 0

    @property
    def nblocks(self) -> int:
        return int(self.D.shape[0])


def _check_blocks(name: str, D, C, b: Optional[int] = None):
    D = np.asarray(D)
    C = np.asarray(C)
    if D.ndim != 3 or D.shape[1] != D.shape[2]:
        raise ValueError(f"{name}: D must be (nblocks, b, b), got {D.shape}")
    if C.shape != D.shape:
        raise ValueError(
            f"{name}: C must ride D {D.shape}, got {C.shape}")
    if b is not None and D.shape[1] != b:
        raise ValueError(
            f"{name}: block size {D.shape[1]} does not match the session's "
            f"b={b}")
    return D, C


class SessionManager:
    """open / append / solve / downdate / close over a SolveEngine.

    Synchronous by design: each method submits one engine request and
    drains it (engine.solve), so the local window mirror and the resident
    factor move in lockstep — the protocol's correctness depends on that
    ordering, not on throughput (batched session throughput comes from
    many sessions, not from pipelining one).

    Methods return the engine's :class:`Response` (callers check ``ok``)
    except when the resident factor was evicted, which raises
    :class:`SessionEvicted` (see module docstring)."""

    def __init__(self, engine):
        self.engine = engine  # guarded-by: <frozen>
        self._sessions: dict[str, _SessionState] = {}  # guarded-by: <owner-thread>
        self._known: set[str] = set()  # guarded-by: <owner-thread>  (ever-opened ids: reseed detection)
        self.opens = 0  # guarded-by: <owner-thread>
        self.reseeds = 0  # guarded-by: <owner-thread>
        self.appends = 0  # guarded-by: <owner-thread>
        self.solves = 0  # guarded-by: <owner-thread>
        self.contracts = 0  # guarded-by: <owner-thread>
        self.closes = 0  # guarded-by: <owner-thread>
        self.failures = 0  # guarded-by: <owner-thread>  (non-eviction failed responses)
        self.evicted_failures = 0  # guarded-by: <owner-thread>  (SessionEvicted conversions)
        self.hits = 0  # guarded-by: <owner-thread>  (resident requests that found state)
        self.misses = 0  # guarded-by: <owner-thread>  (== evicted_failures; see hit_rate)
        self.blocks_appended = 0  # guarded-by: <owner-thread>  (open + append blocks)
        self.blocks_dropped = 0  # guarded-by: <owner-thread>  (contracted blocks)

    # ---- protocol ----------------------------------------------------------

    def open(self, sid: str, D, C, *,
             deadline_ms: Optional[float] = None) -> Response:
        """Seed (or RE-seed) session `sid` from the initial window blocks
        D, C = (nblocks, b, b).  C[0] is ignored (zeroed — the chain head
        has no predecessor).  Re-opening a known id is the sanctioned
        recovery from :class:`SessionEvicted` and counts as a reseed."""
        sid = str(sid)
        D, C = _check_blocks("session open", D, C)
        b = int(D.shape[1])
        A = np.stack([D, C]).astype(D.dtype, copy=False)
        resp = self.engine.solve("session_open", A, factor_token=sid,
                                 deadline_ms=deadline_ms)
        self.opens += 1
        if sid in self._known:
            self.reseeds += 1
        self._known.add(sid)
        if not resp.ok:
            self.failures += 1
            self._sessions.pop(sid, None)
            return resp
        C = np.array(C, copy=True)
        C[0] = 0
        self._sessions[sid] = _SessionState(
            b=b, dtype=D.dtype, D=np.array(D, copy=True), C=C)
        self.blocks_appended += int(D.shape[0])
        return resp

    def append(self, sid: str, D, C, *,
               deadline_ms: Optional[float] = None) -> Response:
        """Extend session `sid` by the NEW blocks D, C = (k, b, b) —
        C[0] is LIVE (it couples the first new block to the current
        window tail).  O(k) work against the resident carry; the window
        mirror grows only when the engine confirms the factor did."""
        sid = str(sid)
        s = self._state(sid)
        D, C = _check_blocks("session append", D, C, s.b)
        A = np.stack([D, C]).astype(s.dtype, copy=False)
        resp = self.engine.solve("session_append", A, factor_token=sid,
                                 deadline_ms=deadline_ms)
        if not resp.ok:
            return self._lose(sid, resp)
        self.hits += 1
        self.appends += 1
        s.appends += 1
        s.D = np.concatenate([s.D, np.asarray(D, dtype=s.dtype)])
        s.C = np.concatenate([s.C, np.asarray(C, dtype=s.dtype)])
        self.blocks_appended += int(D.shape[0])
        return resp

    def solve(self, sid: str, B, *, accuracy_tier: str = "balanced",
              deadline_ms: Optional[float] = None) -> Response:
        """Solve A_window · X = B against the resident factor.  B =
        (nblocks, b, nrhs) rides the CURRENT window; the engine composes
        the [D; C; L; Wt] program operand from the resident chain, so
        the wire cost is one RHS — never the factor."""
        sid = str(sid)
        s = self._state(sid)
        B = np.asarray(B, dtype=s.dtype)
        if B.ndim != 3 or B.shape[0] != s.nblocks or B.shape[1] != s.b:
            raise ValueError(
                f"session solve: B must be (nblocks={s.nblocks}, "
                f"b={s.b}, nrhs), got {B.shape}")
        A = np.stack([s.D, s.C])
        resp = self.engine.solve("session_solve", A, B, factor_token=sid,
                                 accuracy_tier=accuracy_tier,
                                 deadline_ms=deadline_ms)
        if not resp.ok:
            return self._lose(sid, resp)
        self.hits += 1
        self.solves += 1
        s.solves += 1
        return resp

    def contract(self, sid: str, k: int) -> Response:
        """Drop the k OLDEST blocks (sliding-window downdate).  The
        resident factor contracts by a pure slice; the window mirror
        slides and rebuilds its head from the new head factor block the
        engine returns: D[0] ← L_k·L_kᵀ, C[0] ← 0 (the marginal window
        precision — models/blocktri.contract)."""
        sid = str(sid)
        s = self._state(sid)
        k = int(k)
        if not 0 < k < s.nblocks:
            raise ValueError(
                f"session contract: k={k} must satisfy 0 < k < "
                f"nblocks={s.nblocks} (dropping everything is close())")
        resp = self.engine.solve("session_contract", k, factor_token=sid)
        if not resp.ok:
            return self._lose(sid, resp)
        Lk = np.asarray(resp.x)
        self.hits += 1
        self.contracts += 1
        s.contracts += 1
        s.D = np.array(s.D[k:], copy=True)
        s.C = np.array(s.C[k:], copy=True)
        s.D[0] = Lk @ Lk.T
        s.C[0] = 0
        s.dropped += k
        self.blocks_dropped += k
        return resp

    #: protocol alias — `downdate` is the session-protocol name for the
    #: sliding-window contract (symmetry with chol_downdate).
    downdate = contract

    def close(self, sid: str) -> Response:
        """Release the resident factor and the local mirror.  Closing an
        already-gone session is a no-op success (the released flag in
        ``response.x`` says whether a factor was actually resident)."""
        sid = str(sid)
        resp = self.engine.solve("session_close", None, factor_token=sid)
        self._sessions.pop(sid, None)
        self.closes += 1
        return resp

    # ---- window / pivot bookkeeping ---------------------------------------

    def window(self, sid: str):
        """Copies of the session's current (D, C) window blocks — the
        matrix every solve answers for (residual seam for tests)."""
        s = self._state(sid)
        return np.array(s.D, copy=True), np.array(s.C, copy=True)

    def is_open(self, sid: str) -> bool:
        return str(sid) in self._sessions

    def pivot_offset(self, sid: str) -> int:
        """Rows preceding the CURRENT window head in whole-chain
        coordinates (counting every block ever streamed, including
        contracted ones): dropped · b."""
        s = self._state(sid)
        return s.dropped * s.b

    def segment_offset(self, sid: str) -> int:
        """Whole-chain row offset of the NEXT appended segment — equal to
        the offset of the most recent segment when that append FAILED
        (the window did not grow), which is exactly when it is needed."""
        s = self._state(sid)
        return (s.dropped + s.nblocks) * s.b

    def absolute_pivot(self, sid: str, info) -> int:
        """Map a segment-relative breakdown pivot (1-based ``info`` from
        a failed open/append) to the whole chain: every block ever
        streamed through the session counts, contracted ones included."""
        return self.segment_offset(sid) + int(info)

    # ---- internals ---------------------------------------------------------

    def _state(self, sid: str) -> _SessionState:
        s = self._sessions.get(str(sid))
        if s is None:
            raise KeyError(
                f"session {sid!r} is not open here — open() it first "
                "(after SessionEvicted, re-open with a fresh window)")
        return s

    def _lose(self, sid: str, resp: Response) -> Response:
        """Failed-response triage: eviction raises the typed exception
        (dropping the mirror — the resident state is gone); everything
        else returns the failed Response untouched."""
        if resp.error and resp.error.startswith("SessionEvicted:"):
            self.misses += 1
            self.evicted_failures += 1
            self._sessions.pop(str(sid), None)
            raise SessionEvicted(sid, resp.error)
        self.failures += 1
        return resp

    # ---- stats -------------------------------------------------------------

    def stats(self) -> dict:
        """The session_stats counter block (see emit_session_stats)."""
        resolved = self.hits + self.misses
        return {
            "schema_version": SESSION_STATS_SCHEMA,
            "opens": self.opens,
            "reseeds": self.reseeds,
            "appends": self.appends,
            "solves": self.solves,
            "contracts": self.contracts,
            "closes": self.closes,
            "failures": self.failures,
            "evicted_failures": self.evicted_failures,
            "hits": self.hits,
            "misses": self.misses,
            # hit-rate over RESIDENT requests (append/solve/contract):
            # a miss is an evicted factor — priced as a full re-seed
            "hit_rate": (self.hits / resolved) if resolved else 1.0,
            "sessions_open": len(self._sessions),
            "sessions_known": len(self._known),
            "blocks_appended": self.blocks_appended,
            "blocks_dropped": self.blocks_dropped,
        }

    def emit_session_stats(self, path: Optional[str] = None, *,
                           grid=None, config=None, **extra) -> dict:
        """Assemble (and append, when `path` is given) ONE ledger record
        carrying the session counters — kind 'serve:session_stats', same
        manifest discipline as every other ledger row
        (obs.ledger.validate_session_stats)."""
        from capital_tpu.obs import ledger

        rec = ledger.record(
            "serve:session_stats",
            ledger.manifest(grid=grid, config=config or self.engine.cfg),
            session_stats=self.stats(),
            **extra,
        )
        if path:
            ledger.append(path, rec)
        return rec
