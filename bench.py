"""Flagship benchmark: recursive Cholesky + triangular inverse (cholinv).

Times ``cholesky.factor`` — the reference's flagship algorithm
(bench/cholesky/cholinv.cpp) — on the available device(s) and prints ONE
JSON line::

    {"metric": "cholinv_tflops", "value": N, "unit": "TFLOP/s",
     "vs_baseline": N, ...}

``vs_baseline`` is achieved throughput over the north-star target from
BASELINE.md: 90% of the chip's peak dense-matmul throughput at the bench
dtype (the reference publishes no absolute numbers — its repo ships only
the harness — so the target *is* the baseline).  Flop count for Cholesky
factor + triangular inverse: N^3/3 + N^3/3 = 2N^3/3, times 2 sweeps of
useful work counted conservatively as N^3/3 + N^3/3 (factor+inverse).

Timing discipline: the reference driver times warmup + per-iteration walls
(bench/cholesky/cholinv.cpp:44-59).  Dispatch through the TPU tunnel has a
fixed ~70ms overhead and async dispatch means naive host-side walls lie, so
the iteration loop runs INSIDE one jit (lax.fori_loop with a data-dependent
carry), the result is synced by a host transfer, and the per-iteration time
is the delta between an (ITERS+1)-iteration run and a 1-iteration run.

Usage: python bench.py [N] [dtype] [iters] [base_case_dim]
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp


# peak dense-matmul TFLOP/s per chip, by (device kind substring, dtype).
# Public numbers: v4 275 bf16; v5e 197 bf16 / 98.5 f32(fp32 via bf16x3 ~
# counted at 1/2); v5p 459; v6e (Trillium) 918.  f32 figures are bf16/2
# except where the MXU runs f32 natively at 1/8.
_PEAK_BF16 = {
    "v6e": 918.0, "v6": 918.0,
    "v5p": 459.0, "v5": 197.0, "lite": 197.0,
    "v4": 275.0,
    "v3": 123.0, "v2": 45.0,
}


def _peak_tflops(kind: str, dtype) -> float:
    kind = kind.lower()
    peak = 197.0
    for k, v in _PEAK_BF16.items():
        if k in kind:
            peak = v
            break
    if jnp.dtype(dtype).itemsize >= 4:
        peak /= 2.0  # f32 on MXU via 2-pass bf16 (upper bound)
    return peak


def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache next to this file: the n=32768
    flagship program takes ~8-10 min to compile cold on v5e but <1 min from
    cache, so warmed runs (CI/driver re-runs on the same machine) skip the
    wait.  Overridable/disable-able via JAX_COMPILATION_CACHE_DIR=''."""
    cache = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
    )
    if not cache:
        return
    try:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 10.0)
    except Exception:
        pass  # older jax without these flags: cold compile, still correct


def auto_base_case(n: int) -> int:
    """Base-case dim for the flagship: 512 is the committed sweet spot; for
    n that 512 cannot tile exactly (the aligned pallas path needs
    n = bc * 2^k), fall back to the largest 128-multiple that does rather
    than padding — at n=49152 a 512 base would pad to 65536 ((4/3)^3 ≈ 2.4x
    the flops and an HBM OOM).  Candidates must be 128-multiples (the
    pallas view path needs every window offset 128-aligned,
    ops/pallas_tpu._fit_block)."""
    from capital_tpu.models import cholesky

    for cand in (512, 384, 256):
        if cholesky.padded_dim(n, cand) == n:
            return cand
    print(
        f"# warning: no 128-multiple base tiles n={n} exactly; "
        f"padding to {cholesky.padded_dim(n, 512)} "
        f"({cholesky.padded_dim(n, 512)**3 / n**3:.2f}x the flops — "
        "pick n = bc * 2^k to avoid this)",
        file=sys.stderr,
    )
    return 512


def main() -> None:
    _enable_compile_cache()
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 32768
    dtype = jnp.dtype(sys.argv[2]) if len(sys.argv) > 2 else jnp.bfloat16
    iters = int(sys.argv[3]) if len(sys.argv) > 3 else 5

    from capital_tpu.models import cholesky
    from capital_tpu.parallel.topology import Grid

    dev = jax.devices()[0]
    grid = Grid.square(c=1, devices=[dev])

    # argv bc of 0 (or absent) means auto-pick
    bc = (int(sys.argv[4]) if len(sys.argv) > 4 else 0) or auto_base_case(n)
    # bf16 throughput config: trailing updates at the MXU's native precision
    # through the pallas dead-block-skipping kernels, base case in f32
    # (CholinvConfig default picks f32 for narrow inputs)
    cfg = cholesky.CholinvConfig(
        base_case_dim=bc,
        mode="pallas",
        precision=None if jnp.dtype(dtype).itemsize < 4 else "highest",
    )

    # well-conditioned SPD operand, generated on device (shared helper:
    # 3I diagonal shift — the Wigner edge sits at exactly 2, so a 2I shift
    # can graze a zero eigenvalue and NaN an f32/bf16 factorization
    # depending on the RNG stream; an f32 host staging array would also be
    # a 4.3GB transient at n=32768)
    from capital_tpu.bench.drivers import _spd

    A = _spd(n, dtype)

    @jax.jit
    def loop(a, eps, iters):
        def body(_, carry):
            R, Rinv = cholesky.factor(grid, carry, cfg)
            # data-dependent carry consuming BOTH outputs: eps is a runtime
            # scalar (0.0 at call time) so XLA cannot fold the perturbation
            # away and dead-code-eliminate the factorization.  Consuming one
            # element of each output is sufficient — R/Rinv are produced by
            # chains of aliased pallas custom calls XLA cannot slice through,
            # so every kernel still runs (verified on-device: elem-coupling
            # 37.6 ms/iter vs 38.3 for full-sum consumption vs 18.0 when the
            # Rinv chain is *actually* DCE'd, n=16k).  Consuming only R would
            # kill the inverse-completion half of the work; a full-matrix
            # carry add (carry + eps*(R+Rinv)) costs ~4 extra HBM passes of
            # pure harness overhead (~10 ms/iter at n=32k).
            d = R[0, 0] + Rinv[0, 0]
            return carry.at[0, 0].add(eps.astype(carry.dtype) * d)

        out = jax.lax.fori_loop(0, iters, body, a)
        return jnp.sum(out, dtype=jnp.float32)

    eps = jnp.asarray(0.0, jnp.float32)

    def timed(k: int) -> float:
        t0 = time.perf_counter()
        float(loop(A, eps, k))  # host transfer = real sync
        return time.perf_counter() - t0

    from capital_tpu.bench import harness

    timed(1)  # warmup: compile (dynamic trip count -> one executable)
    timed(1)  # second warmup: let clocks/tunnel state settle post-compile
    # Interleaved (base, full) pairs + median — the one protocol shared with
    # harness.timed_loop; see paired_median_delta for the drift-bias story.
    def run(k: int) -> float:
        return timed(k)

    t, delta = harness.paired_median_delta(run, iters, 8)
    noise = harness.noise_band_seconds()
    while iters < 512 and delta < noise:
        # small-n runs: grow the in-jit loop until the delta clears the band
        grow = int(3.0 * noise / t) if t > 0.0 else iters * 8
        iters = min(512, max(iters * 2, grow))
        t, delta = harness.paired_median_delta(run, iters, 5)
    if t <= 0.0 or delta < noise:
        raise SystemExit(
            f"measurement unresolved: delta {delta:.3e}s at {iters} "
            "iterations is inside the dispatch-noise band"
        )

    flops = 2.0 * n**3 / 3.0  # factor (n^3/3) + full triangular inverse (n^3/3)
    tflops = flops / t / 1e12
    target = 0.9 * _peak_tflops(dev.device_kind, dtype)

    print(
        json.dumps(
            {
                "metric": "cholinv_tflops",
                "value": round(tflops, 3),
                "unit": "TFLOP/s",
                "vs_baseline": round(tflops / target, 4),
                "n": n,
                "bc": bc,
                "dtype": str(jnp.dtype(dtype)),
                "seconds": round(t, 4),
                "device": dev.device_kind,
                "target_tflops": round(target, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
