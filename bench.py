"""Flagship benchmark: recursive Cholesky + triangular inverse (cholinv).

Times ``cholesky.factor`` — the reference's flagship algorithm
(bench/cholesky/cholinv.cpp) — on the available device(s) and prints ONE
JSON line::

    {"metric": "cholinv_tflops", "value": N, "unit": "TFLOP/s",
     "vs_baseline": N, ...}

``vs_baseline`` is achieved throughput over the north-star target from
BASELINE.md: 90% of the chip's peak dense-matmul throughput at the bench
dtype (the reference publishes no absolute numbers — its repo ships only
the harness — so the target *is* the baseline).  Flop count for Cholesky
factor + triangular inverse: N^3/3 + N^3/3 = 2N^3/3, times 2 sweeps of
useful work counted conservatively as N^3/3 + N^3/3 (factor+inverse).

Timing discipline: the reference driver times warmup + per-iteration walls
(bench/cholesky/cholinv.cpp:44-59).  Dispatch through the TPU tunnel has a
fixed ~70ms overhead and async dispatch means naive host-side walls lie, so
the iteration loop runs INSIDE one jit (lax.fori_loop with a data-dependent
carry), the result is synced by a host transfer, and the per-iteration time
is the delta between an (ITERS+1)-iteration run and a 1-iteration run.

Usage: python bench.py [N] [dtype] [iters] [base_case_dim] [precision]
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp


# peak dense-matmul TFLOP/s per chip, by (device kind substring, dtype).
# Public numbers: v4 275 bf16; v5e 197 bf16 / 98.5 f32(fp32 via bf16x3 ~
# counted at 1/2); v5p 459; v6e (Trillium) 918.  f32 figures are bf16/2
# except where the MXU runs f32 natively at 1/8.
_PEAK_BF16 = {
    "v6e": 918.0, "v6": 918.0,
    "v5p": 459.0, "v5": 197.0, "lite": 197.0,
    "v4": 275.0,
    "v3": 123.0, "v2": 45.0,
}


def _peak_tflops(kind: str, dtype) -> float:
    kind = kind.lower()
    peak = 197.0
    for k, v in _PEAK_BF16.items():
        if k in kind:
            peak = v
            break
    if jnp.dtype(dtype).itemsize >= 4:
        peak /= 2.0  # f32 on MXU via 2-pass bf16 (upper bound)
    return peak


def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache next to this file: the n=32768
    flagship program takes ~8-10 min to compile cold on v5e but <1 min from
    cache, so warmed runs (CI/driver re-runs on the same machine) skip the
    wait.  Overridable/disable-able via JAX_COMPILATION_CACHE_DIR=''."""
    cache = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
    )
    if not cache:
        return
    try:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 10.0)
    except Exception:
        pass  # older jax without these flags: cold compile, still correct


def auto_base_case(n: int) -> int:
    """Base-case dim for the flagship: 512 is the committed sweet spot; for
    n that 512 cannot tile exactly (the aligned pallas path needs
    n = bc * 2^k), fall back to the largest 128-multiple that does rather
    than padding — at n=49152 a 512 base would pad to 65536 ((4/3)^3 ≈ 2.4x
    the flops and an HBM OOM).  Candidates must be 128-multiples (the
    pallas view path needs every window offset 128-aligned,
    ops/pallas_tpu._fit_block).  When nothing tiles exactly, pick the
    candidate minimizing the padded dim (least wasted flops), not blindly
    512 — and warn; main() also records the padded dim in the JSON line so
    non-interactive consumers see the cost."""
    from capital_tpu.bench.drivers import pick_bc
    from capital_tpu.models import cholesky

    # ONE picker shared with the drivers (padding-aware; below the
    # small-N crossovers finer leaves shorten the latency-bound potrf
    # chain — docs/PERF.md "Small-N — round 5")
    best = pick_bc(n)
    if cholesky.padded_dim(n, best) == n:
        return best
    print(
        f"# warning: no 128-multiple base tiles n={n} exactly; "
        f"padding to {cholesky.padded_dim(n, best)} with bc={best} "
        f"({cholesky.padded_dim(n, best)**3 / n**3:.2f}x the flops — "
        "pick n = bc * 2^k to avoid this)",
        file=sys.stderr,
    )
    return best


def spd_hash(n: int, dtype, salt) -> "jnp.ndarray":
    """Deterministic well-conditioned SPD matrix as ONE fused elementwise
    program — no RNG bit buffers, no transpose pass, exactly one n x n
    output allocation.  Used by the one-shot loop, which must re-materialize
    a fresh operand EVERY iteration (salt = loop index, so XLA cannot hoist
    it) while three factor-sized buffers are already resident.

    Entries: symmetric splitmix32-style hash of (min(i,j), max(i,j), salt)
    mapped to U[-1, 1]/sqrt(n), plus a 3I shift.  Spectral norm of the
    random part ≈ 2·sqrt(n·Var) = 2/sqrt(3) ≈ 1.16, so the spectrum sits in
    ~[1.8, 4.2]: safely SPD at bf16 like _spd's Wigner operand (same 3I
    margin — see capital_tpu/bench/drivers.py:_spd on why not 2I)."""
    from jax import lax

    r = lax.broadcasted_iota(jnp.uint32, (n, n), 0)
    c = lax.broadcasted_iota(jnp.uint32, (n, n), 1)
    lo, hi = jnp.minimum(r, c), jnp.maximum(r, c)
    h = lo * jnp.uint32(0x9E3779B1) ^ hi * jnp.uint32(0x85EBCA77)
    h = h + jnp.asarray(salt).astype(jnp.uint32) * jnp.uint32(0xC2B2AE3D)
    h = (h ^ (h >> 16)) * jnp.uint32(0x7FEB352D)
    h = (h ^ (h >> 15)) * jnp.uint32(0x846CA68B)
    h = h ^ (h >> 16)
    u = h.astype(jnp.float32) * jnp.float32(2.0**-32)  # [0, 1)
    v = (2.0 * u - 1.0) * jnp.float32(1.0 / float(n) ** 0.5)
    v = v + jnp.where(r == c, jnp.float32(3.0), jnp.float32(0.0))
    return v.astype(dtype)


def main() -> None:
    _enable_compile_cache()
    # default 49152, not 32768: the larger size amortizes the diagonal-band
    # masking and base-case latency floors (169.3-169.9 TF/s = 0.955-0.958
    # vs 156.8-157.1 = 0.886 at 32768, three runs each) and is the largest
    # bc·2^k that fits one v5e in the one-shot 3-buffer protocol below
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 49152
    dtype = jnp.dtype(sys.argv[2]) if len(sys.argv) > 2 else jnp.bfloat16
    iters = int(sys.argv[3]) if len(sys.argv) > 3 else 5
    # argv[5]: matmul precision override for >= f32 dtypes ('high' = the
    # in-kernel bf16x3 3-pass — f32-grade residuals at ~1.6x the default
    # 6-pass 'highest' rate; docs/PERF.md "f32 round 4")
    precision = sys.argv[5] if len(sys.argv) > 5 else None

    from capital_tpu.models import cholesky
    from capital_tpu.parallel.topology import Grid

    dev = jax.devices()[0]
    grid = Grid.square(c=1, devices=[dev])

    # argv bc of 0 (or absent) means auto-pick
    bc = (int(sys.argv[4]) if len(sys.argv) > 4 else 0) or auto_base_case(n)
    padded = cholesky.padded_dim(n, bc)

    # One-shot mode for sizes whose 3-buffer resident set (operand carry +
    # R + Rinv + the materialized Schur chain, ~3.35 n² at bf16) cannot fit
    # one chip's HBM: the loop re-materializes a fresh operand per iteration
    # (spd_hash of the loop index — one fused n² write) and factors it with
    # schur_in_place, so peak memory is exactly 3 n² buffers (operand — dead
    # after its last Schur read — plus the two factor buffers with every
    # Schur update aliased in place).  n=49152 bf16: 14.5 GB vs 15.75;
    # round-2's carry-mode attempt measured "Used 19.42G".  The regen cost
    # is measured by a second loop with the factor removed and subtracted.
    kind = dev.device_kind.lower()
    if "v6" in kind:  # v6e is "TPU v6 lite": match before the v5e 'lite' test
        hbm = 30e9
    elif "v5p" in kind:
        hbm = 90e9
    elif "v4" in kind:
        hbm = 30e9
    elif "lite" in kind or "v5e" in kind:
        hbm = 15.5e9
    else:
        # unknown chips: assume SMALL — wrongly enabling one-shot only
        # changes the protocol (still correct); wrongly assuming big HBM
        # reproduces the round-2 compile-time OOM
        hbm = 15.5e9
    oneshot = 3.35 * padded * padded * jnp.dtype(dtype).itemsize > hbm
    if os.environ.get("CAPITAL_BENCH_ONESHOT") in ("0", "1"):  # A/B override
        oneshot = os.environ["CAPITAL_BENCH_ONESHOT"] == "1"
    cfg = cholesky.CholinvConfig(
        base_case_dim=bc,
        mode="pallas",
        precision=(
            None if jnp.dtype(dtype).itemsize < 4 else (precision or "highest")
        ),
        schur_in_place=oneshot,
    )

    from capital_tpu.bench import harness

    eps = jnp.asarray(0.0, jnp.float32)

    if oneshot:
        if padded != n:
            # cropped outputs cannot serve as the next iteration's p x p
            # buffers; untileable n pays the hoisted-zeros copies instead
            raise SystemExit(
                f"oneshot mode needs n = bc * 2^k (n={n} pads to {padded}); "
                "pick a tiling size — see auto_base_case"
            )

        @jax.jit
        def loop(eps, iters):
            def body(i, carry):
                acc, Rp, RIp = carry
                # optimization_barrier pins the generator as a materialized
                # n² buffer in BOTH loops (without it the regen-only loop's
                # one-element consumption would let XLA narrow the fused
                # generator to a single element and the subtraction would
                # over-credit the factor)
                a = jax.lax.optimization_barrier(spd_hash(n, dtype, i))
                # the factor buffers are loop CARRIES: each iteration
                # factors into the previous outputs (every upper tile is
                # rewritten, the dead lower zeros are never touched) —
                # without this, XLA hoists the loop-invariant zero-init
                # and re-copies both buffers every iteration before the
                # first aliased write (2 x 3.27 ms/iter at n=49152)
                R, Rinv = cholesky.factor(grid, a, cfg, out_buffers=(Rp, RIp))
                d = R[0, 0] + Rinv[0, 0]
                return acc + eps * d.astype(jnp.float32), R, Rinv

            Rp0, RIp0 = cholesky.factor_buffers(grid, n, dtype, cfg)
            out, _, _ = jax.lax.fori_loop(
                0, iters, body, (jnp.float32(0.0), Rp0, RIp0)
            )
            return out

        @jax.jit
        def loop_regen(eps, iters):
            def body(i, carry):
                a = jax.lax.optimization_barrier(spd_hash(n, dtype, i))
                return carry + eps * a[0, 0].astype(jnp.float32)

            return jax.lax.fori_loop(0, iters, body, jnp.float32(0.0))

        def timed(k: int) -> float:
            t0 = time.perf_counter()
            float(loop(eps, k))
            return time.perf_counter() - t0

        def timed_regen(k: int) -> float:
            t0 = time.perf_counter()
            float(loop_regen(eps, k))
            return time.perf_counter() - t0
    else:
        # standard mode: the operand is the loop carry (no per-iteration
        # regeneration; ~3.35 n² resident is fine at these sizes)
        from capital_tpu.bench.drivers import _spd

        # well-conditioned SPD operand, generated on device (shared helper:
        # 3I diagonal shift — the Wigner edge sits at exactly 2, so a 2I
        # shift can graze a zero eigenvalue and NaN an f32/bf16 factorization
        # depending on the RNG stream; an f32 host staging array would also
        # be a 4.3GB transient at n=32768)
        A = _spd(n, dtype)

        @jax.jit
        def loop(a, eps, iters):
            def body(_, carry):
                R, Rinv = cholesky.factor(grid, carry, cfg)
                # data-dependent carry consuming BOTH outputs: eps is a
                # runtime scalar (0.0 at call time) so XLA cannot fold the
                # perturbation away and dead-code-eliminate the
                # factorization.  Consuming one element of each output is
                # sufficient — R/Rinv are produced by chains of aliased
                # pallas custom calls XLA cannot slice through, so every
                # kernel still runs (verified on-device: elem-coupling 37.6
                # ms/iter vs 38.3 for full-sum consumption vs 18.0 when the
                # Rinv chain is *actually* DCE'd, n=16k).  Consuming only R
                # would kill the inverse-completion half of the work; a
                # full-matrix carry add (carry + eps*(R+Rinv)) costs ~4
                # extra HBM passes of pure harness overhead (~10 ms/iter at
                # n=32k).
                d = R[0, 0] + Rinv[0, 0]
                return carry.at[0, 0].add(eps.astype(carry.dtype) * d)

            out = jax.lax.fori_loop(0, iters, body, a)
            return jnp.sum(out, dtype=jnp.float32)

        def timed(k: int) -> float:
            t0 = time.perf_counter()
            float(loop(A, eps, k))  # host transfer = real sync
            return time.perf_counter() - t0

        timed_regen = None

    timed(1)  # warmup: compile (dynamic trip count -> one executable)
    timed(1)  # second warmup: let clocks/tunnel state settle post-compile
    # Interleaved (base, full) pairs + median — the one protocol shared with
    # harness.timed_loop; see paired_median_delta for the drift-bias story.
    t, delta = harness.paired_median_delta(timed, iters, 8)
    noise = harness.noise_band_seconds()
    while iters < 512 and delta < noise:
        # small-n runs: grow the in-jit loop until the delta clears the band
        grow = int(3.0 * noise / t) if t > 0.0 else iters * 8
        iters = min(512, max(iters * 2, grow))
        t, delta = harness.paired_median_delta(timed, iters, 5)
    if t <= 0.0 or delta < noise:
        raise SystemExit(
            f"measurement unresolved: delta {delta:.3e}s at {iters} "
            "iterations is inside the dispatch-noise band"
        )

    t_regen = 0.0
    if oneshot:
        timed_regen(1)  # compile the regen-only loop
        # the regen step (~one fused n² write) is far below the factor but
        # must clear the noise band on its own; grow its trip count
        # independently (cheap — no factor inside)
        kr = max(iters, 16)
        t_regen, dr = harness.paired_median_delta(timed_regen, kr, 8)
        while kr < 4096 and dr < noise:
            kr = min(4096, max(kr * 2, int(3.0 * noise / max(t_regen, 1e-9))))
            t_regen, dr = harness.paired_median_delta(timed_regen, kr, 5)
        if t_regen < 0.0 or dr < noise:
            raise SystemExit(
                f"regen measurement unresolved: delta {dr:.3e}s at {kr} "
                "iterations is inside the dispatch-noise band"
            )
        t = t - t_regen
        # the SUBTRACTED time is the reported quantity: it must itself be
        # positive and clear the band over the measured trip count, else
        # the factor is measurement noise riding on two valid loops (small
        # n under the A/B override: two medians can jitter past each other
        # and print a negative or infinite TF/s)
        if t <= 0.0 or t * iters < noise:
            raise SystemExit(
                f"oneshot measurement unresolved: factor-only time "
                f"{t:.3e}s/iter after regen subtraction is inside the "
                "dispatch-noise band"
            )

    flops = 2.0 * n**3 / 3.0  # factor (n^3/3) + full triangular inverse (n^3/3)
    tflops = flops / t / 1e12
    target = 0.9 * _peak_tflops(dev.device_kind, dtype)

    rec = {
        "metric": "cholinv_tflops",
        "value": round(tflops, 3),
        "unit": "TFLOP/s",
        "vs_baseline": round(tflops / target, 4),
        "n": n,
        "bc": bc,
        "dtype": str(jnp.dtype(dtype)),
        "seconds": round(t, 4),
        "device": dev.device_kind,
        "target_tflops": round(target, 1),
    }
    if padded != n:
        rec["padded"] = padded  # flops above count n³, not the executed padded³
    if oneshot:
        rec["oneshot"] = True
        rec["regen_seconds"] = round(t_regen, 4)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
